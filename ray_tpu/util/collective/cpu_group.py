"""CPU collective group: TCP mesh between members, GCS-KV rendezvous.

The Gloo-class backend (reference:
python/ray/util/collective/collective_group/gloo_collective_group.py) —
each member runs a listener; addresses rendezvous through the GCS KV;
peers connect lazily.  Reductions use a ring for large arrays
(reduce-scatter + allgather) and a star through rank 0 for small ones.
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
import time
from typing import Any, Dict, List, Optional

import numpy as np

_LEN = struct.Struct("<Q")
KV_NS = "collective"
RING_THRESHOLD = 1 << 20  # 1MB: below this a star is faster than a ring

REDUCE_OPS = {
    "sum": np.add,
    "product": np.multiply,
    "min": np.minimum,
    "max": np.maximum,
}


def _send_msg(sock: socket.socket, obj: Any):
    data = pickle.dumps(obj, protocol=5)
    sock.sendall(_LEN.pack(len(data)) + data)

def _recv_msg(sock: socket.socket) -> Any:
    hdr = _recv_exact(sock, _LEN.size)
    (length,) = _LEN.unpack(hdr)
    return pickle.loads(_recv_exact(sock, length))


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    while n:
        c = sock.recv(min(n, 1 << 20))
        if not c:
            raise ConnectionError("collective peer closed")
        chunks.append(c)
        n -= len(c)
    return b"".join(chunks)


class CPUCollectiveGroup:
    def __init__(self, world_size: int, rank: int, group_name: str, kv):
        self.world_size = world_size
        self.rank = rank
        self.group_name = group_name
        self._kv = kv  # callable kv interface: put(key, val), get(key)
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", 0))
        self._listener.listen(world_size)
        self._addr = self._listener.getsockname()
        self._peers: Dict[int, socket.socket] = {}
        self._peer_locks: Dict[int, threading.Lock] = {}
        self._accept_thread = threading.Thread(target=self._accept_loop, daemon=True)
        self._accepted: Dict[int, socket.socket] = {}
        self._accept_cond = threading.Condition()
        self._closed = False
        self._accept_thread.start()
        self._rendezvous()

    # -- rendezvous through GCS KV ----------------------------------------
    def _key(self, rank: int) -> bytes:
        return f"{self.group_name}/{rank}".encode()

    def _rendezvous(self, timeout: float = 60.0):
        self._kv_put(self._key(self.rank), pickle.dumps(self._addr))
        deadline = time.monotonic() + timeout
        self._peer_addrs = {}
        for r in range(self.world_size):
            if r == self.rank:
                continue
            while True:
                blob = self._kv_get(self._key(r))
                if blob is not None:
                    self._peer_addrs[r] = pickle.loads(blob)
                    break
                if time.monotonic() > deadline:
                    raise TimeoutError(f"rank {r} never joined group {self.group_name}")
                time.sleep(0.02)

    def _kv_put(self, key: bytes, val: bytes):
        self._kv("kv_put", (KV_NS, key, val, True))

    def _kv_get(self, key: bytes) -> Optional[bytes]:
        return self._kv("kv_get", (KV_NS, key))

    # -- connections -------------------------------------------------------
    def _accept_loop(self):
        while not self._closed:
            try:
                conn, _ = self._listener.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            peer_rank = _recv_msg(conn)
            with self._accept_cond:
                self._accepted[peer_rank] = conn
                self._accept_cond.notify_all()

    def _peer(self, rank: int) -> socket.socket:
        """Connection to a peer.  Lower rank dials; higher rank accepts —
        one deterministic connection per pair."""
        if rank in self._peers:
            return self._peers[rank]
        if self.rank < rank:
            s = socket.create_connection(self._peer_addrs[rank], timeout=30)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            _send_msg(s, self.rank)
        else:
            with self._accept_cond:
                while rank not in self._accepted:
                    if not self._accept_cond.wait(timeout=30):
                        raise TimeoutError(f"rank {rank} never connected")
                s = self._accepted.pop(rank)
        self._peers[rank] = s
        self._peer_locks[rank] = threading.Lock()
        return s

    # -- point to point ----------------------------------------------------
    def send(self, tensor, dst_rank: int):
        s = self._peer(dst_rank)
        with self._peer_locks[dst_rank]:
            _send_msg(s, np.asarray(tensor))

    def recv(self, shape, dtype, src_rank: int):
        s = self._peer(src_rank)
        return _recv_msg(s)

    # -- collectives -------------------------------------------------------
    def broadcast(self, tensor, src_rank: int = 0):
        arr = np.asarray(tensor)
        if self.rank == src_rank:
            for r in range(self.world_size):
                if r != self.rank:
                    self.send(arr, r)
            return arr
        return self.recv(None, None, src_rank)

    def reduce(self, tensor, dst_rank: int = 0, op: str = "sum"):
        arr = np.asarray(tensor)
        if self.rank == dst_rank:
            acc = arr.copy()
            for r in range(self.world_size):
                if r != self.rank:
                    acc = REDUCE_OPS[op](acc, self.recv(None, None, r))
            return acc
        self.send(arr, dst_rank)
        return arr

    def allreduce(self, tensor, op: str = "sum"):
        arr = np.asarray(tensor)
        if self.world_size == 1:
            return arr
        if arr.nbytes < RING_THRESHOLD:
            out = self.reduce(arr, 0, op)
            return self.broadcast(out, 0)
        return self._ring_allreduce(arr, op)

    def _ring_allreduce(self, arr: np.ndarray, op: str):
        """Bandwidth-optimal ring: reduce-scatter then allgather."""
        n = self.world_size
        flat = arr.reshape(-1).copy()
        chunks = np.array_split(flat, n)
        right = (self.rank + 1) % n
        left = (self.rank - 1) % n
        # reduce-scatter
        for step in range(n - 1):
            send_idx = (self.rank - step) % n
            recv_idx = (self.rank - step - 1) % n
            self.send(chunks[send_idx], right)
            incoming = self.recv(None, None, left)
            chunks[recv_idx] = REDUCE_OPS[op](chunks[recv_idx], incoming)
        # allgather
        for step in range(n - 1):
            send_idx = (self.rank - step + 1) % n
            recv_idx = (self.rank - step) % n
            self.send(chunks[send_idx], right)
            chunks[recv_idx] = self.recv(None, None, left)
        return np.concatenate(chunks).reshape(arr.shape)

    def allgather(self, tensor):
        arr = np.asarray(tensor)
        out: List[np.ndarray] = [None] * self.world_size  # type: ignore
        out[self.rank] = arr
        # Simple doubling-free exchange: everyone sends to everyone.
        for r in range(self.world_size):
            if r == self.rank:
                continue
            if self.rank < r:
                self.send(arr, r)
                out[r] = self.recv(None, None, r)
            else:
                out[r] = self.recv(None, None, r)
                self.send(arr, r)
        return out

    def reducescatter(self, tensor, op: str = "sum"):
        arr = np.asarray(tensor)
        reduced = self.allreduce(arr, op)
        return np.array_split(reduced.reshape(-1), self.world_size)[self.rank]

    def barrier(self):
        self.allreduce(np.zeros(1, np.float32))

    def destroy(self):
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        for s in self._peers.values():
            try:
                s.close()
            except OSError:
                pass
