"""Distributed trace-context propagation (reference:
python/ray/util/tracing/tracing_helper.py — W3C traceparent carried in
task metadata so spans nest across task/actor boundaries).

Standalone by design (the image ships no OpenTelemetry SDK): context is
a W3C ``traceparent`` string ("00-<trace_id:32>-<span_id:16>-01")
propagated via TaskSpec.trace_parent.  Submitting a task stamps the
caller's current context onto the spec; the executing worker installs a
child context before running the task body, so ``get_trace_id()`` is
stable across an entire distributed call tree and every task event
row carries (trace_id, span_id, parent_span_id) — the timeline and any
external collector can reassemble the tree.

If an OpenTelemetry SDK IS importable, ``use_opentelemetry()`` bridges
span starts/ends to a real tracer.
"""

from __future__ import annotations

import contextvars
import os
import secrets
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

_ctx: contextvars.ContextVar = contextvars.ContextVar("ray_tpu_trace", default=None)
_otel_tracer = None
# process-local span log (drained by tests/exporters)
_finished_spans: List[Dict[str, Any]] = []
_MAX_SPANS = 10_000


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    if not header:
        return None
    parts = header.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


def get_trace_id() -> Optional[str]:
    cur = _ctx.get()
    return cur[0] if cur else None


def get_span_id() -> Optional[str]:
    cur = _ctx.get()
    return cur[1] if cur else None


def current_traceparent() -> Optional[str]:
    """The header to stamp on outgoing work (None when not tracing)."""
    cur = _ctx.get()
    if cur is None:
        return None
    return format_traceparent(cur[0], cur[1])


def install_context(traceparent: Optional[str]) -> None:
    """Executor side: enter a CHILD context of the received header (a
    fresh span id whose parent is the caller's span)."""
    parsed = parse_traceparent(traceparent)
    if parsed is None:
        _ctx.set(None)
        return
    trace_id, parent_span = parsed
    _ctx.set((trace_id, _new_span_id(), parent_span))


@contextmanager
def start_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Open a span under the current context (starting a new trace if
    none is active); spans land in the process span log and, when
    bridged, the OpenTelemetry tracer."""
    prev = _ctx.get()
    if prev is None:
        trace_id, parent = _new_trace_id(), None
    else:
        trace_id, parent = prev[0], prev[1]
    span_id = _new_span_id()
    token = _ctx.set((trace_id, span_id, parent))
    start = time.time()
    otel_cm = None
    if _otel_tracer is not None:
        otel_cm = _otel_tracer.start_as_current_span(name)
        otel_cm.__enter__()
    try:
        yield SpanHandle(trace_id, span_id)
    finally:
        if otel_cm is not None:
            otel_cm.__exit__(None, None, None)
        _record_span(
            {
                "name": name,
                "trace_id": trace_id,
                "span_id": span_id,
                "parent_span_id": parent,
                "start_time": start,
                "end_time": time.time(),
                "pid": os.getpid(),
                "attributes": attributes or {},
            }
        )
        _ctx.reset(token)


class SpanHandle:
    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


def _record_span(span: Dict[str, Any]) -> None:
    _finished_spans.append(span)
    if len(_finished_spans) > _MAX_SPANS:
        del _finished_spans[: len(_finished_spans) - _MAX_SPANS]


def drain_spans() -> List[Dict[str, Any]]:
    """Pop and return this process's finished spans."""
    out, _finished_spans[:] = list(_finished_spans), []
    return out


def use_opentelemetry(tracer=None) -> bool:
    """Bridge spans to an OpenTelemetry tracer if the SDK is available
    (reference: tracing_helper's use of opentelemetry.trace)."""
    global _otel_tracer
    if tracer is not None:
        _otel_tracer = tracer
        return True
    try:
        from opentelemetry import trace as otel_trace

        _otel_tracer = otel_trace.get_tracer("ray_tpu")
        return True
    except Exception:
        return False
