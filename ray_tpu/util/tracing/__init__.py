"""Distributed trace-context propagation (reference:
python/ray/util/tracing/tracing_helper.py — W3C traceparent carried in
task metadata so spans nest across task/actor boundaries).

Standalone by design (the image ships no OpenTelemetry SDK): context is
a W3C ``traceparent`` string ("00-<trace_id:32>-<span_id:16>-01")
propagated via TaskSpec.trace_parent.  Submitting a task stamps the
caller's current context onto the spec; the executing worker installs a
child context before running the task body, so ``get_trace_id()`` is
stable across an entire distributed call tree and every task event
row carries (trace_id, span_id, parent_span_id) — the timeline and any
external collector can reassemble the tree.

If an OpenTelemetry SDK IS importable, ``use_opentelemetry()`` bridges
span starts/ends to a real tracer.
"""

from __future__ import annotations

import contextvars
import os
import secrets
import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, List, Optional, Tuple

_ctx: contextvars.ContextVar = contextvars.ContextVar("ray_tpu_trace", default=None)
_otel_tracer = None
# process-local span log (drained by tests/exporters; shipped off-box by
# the background flusher — see flush())
_finished_spans: List[Dict[str, Any]] = []
_MAX_SPANS = 10_000
_span_lock = threading.Lock()
# Index into _finished_spans up to which the flusher already shipped
# spans to the GCS span table.  The flusher never REMOVES spans, so
# drain_spans() keeps its pop-everything semantics for local consumers.
_flushed_upto = 0
_flusher_started = False
# Concurrency bookkeeping for flush(): ring-buffer trims and drains
# shift/clear indices while a report RPC is in flight; these counters
# let the post-report cursor advance account for that instead of
# skipping (and silently dropping) spans recorded mid-flight.
_trim_total = 0
_drain_epoch = 0


def _new_trace_id() -> str:
    return secrets.token_hex(16)


def _new_span_id() -> str:
    return secrets.token_hex(8)


def new_span_id() -> str:
    """Mint a span id (public: channel hops mint per-frame write spans)."""
    return _new_span_id()


def set_frame_context(frame_ctx: Optional[Tuple[str, str]]) -> Any:
    """Adopt an inbound dataplane frame's trace context: enter a child
    of ``(trace_id, parent_span_id)`` — or CLEAR the context when the
    frame is untraced (``None``), so an executor that serves many
    requests never parents one request's spans under a stale context
    captured at actor start.  Returns a token for :func:`reset_context`."""
    if frame_ctx is None:
        return _ctx.set(None)
    return _ctx.set((frame_ctx[0], _new_span_id(), frame_ctx[1]))


def reset_context(token: Any) -> None:
    """Undo a :func:`set_frame_context` (restores the previous context)."""
    _ctx.reset(token)


def adopt_context(
    ctx: Optional[Tuple[str, str, Optional[str]]]
) -> Any:
    """Set this thread's context to an EXACT ``(trace_id, span_id,
    parent_span_id)`` tuple (or ``None``) without minting — for worker
    threads (e.g. a channel tx thread) acting on behalf of a task whose
    span the tuple names.  Returns a token for :func:`reset_context`."""
    return _ctx.set(ctx)


def format_traceparent(trace_id: str, span_id: str) -> str:
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(header: Optional[str]) -> Optional[Tuple[str, str]]:
    if not header:
        return None
    parts = header.split("-")
    if len(parts) != 4 or len(parts[1]) != 32 or len(parts[2]) != 16:
        return None
    return parts[1], parts[2]


def get_trace_id() -> Optional[str]:
    cur = _ctx.get()
    return cur[0] if cur else None


def current_context() -> Optional[Tuple[str, str, Optional[str]]]:
    """(trace_id, span_id, parent_span_id) of the active context, or None.
    The executor side uses this to record the task's own span — the span
    id minted by install_context IS the task span, so recording it (rather
    than opening a fresh child) keeps parent links intact across the
    process hop."""
    return _ctx.get()


def get_span_id() -> Optional[str]:
    cur = _ctx.get()
    return cur[1] if cur else None


def current_traceparent() -> Optional[str]:
    """The header to stamp on outgoing work (None when not tracing)."""
    cur = _ctx.get()
    if cur is None:
        return None
    return format_traceparent(cur[0], cur[1])


def install_context(traceparent: Optional[str]) -> None:
    """Executor side: enter a CHILD context of the received header (a
    fresh span id whose parent is the caller's span)."""
    parsed = parse_traceparent(traceparent)
    if parsed is None:
        _ctx.set(None)
        return
    trace_id, parent_span = parsed
    _ctx.set((trace_id, _new_span_id(), parent_span))


@contextmanager
def start_span(name: str, attributes: Optional[Dict[str, Any]] = None):
    """Open a span under the current context (starting a new trace if
    none is active); spans land in the process span log and, when
    bridged, the OpenTelemetry tracer."""
    prev = _ctx.get()
    if prev is None:
        trace_id, parent = _new_trace_id(), None
    else:
        trace_id, parent = prev[0], prev[1]
    span_id = _new_span_id()
    token = _ctx.set((trace_id, span_id, parent))
    start = time.time()
    otel_cm = None
    if _otel_tracer is not None:
        otel_cm = _otel_tracer.start_as_current_span(name)
        otel_cm.__enter__()
    try:
        yield SpanHandle(trace_id, span_id)
    finally:
        if otel_cm is not None:
            otel_cm.__exit__(None, None, None)
        _record_span(
            {
                "name": name,
                "trace_id": trace_id,
                "span_id": span_id,
                "parent_span_id": parent,
                "start_time": start,
                "end_time": time.time(),
                "pid": os.getpid(),
                "attributes": attributes or {},
            }
        )
        _ctx.reset(token)


class SpanHandle:
    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id: str, span_id: str):
        self.trace_id = trace_id
        self.span_id = span_id


def _sampled(trace_id: Optional[str]) -> bool:
    """Head sampling, deterministic in the trace id: every process keeps
    or drops the SAME traces, so sampled trees stay whole across hops.
    Spans with no trace id (shouldn't happen) are kept."""
    from ray_tpu._private.config import CONFIG

    try:
        rate = float(CONFIG.span_sample_rate)
    except Exception:
        return True
    if rate >= 1.0:
        return True
    if rate <= 0.0 or not trace_id:
        return rate > 0.0
    try:
        bucket = int(trace_id[:8], 16) / float(0xFFFFFFFF)
    except ValueError:
        return True
    return bucket < rate


def _record_span(span: Dict[str, Any]) -> None:
    global _flushed_upto, _trim_total
    if not _sampled(span.get("trace_id")):
        return
    span.setdefault("tid", threading.get_ident())
    with _span_lock:
        _finished_spans.append(span)
        if len(_finished_spans) > _MAX_SPANS:
            trim = len(_finished_spans) - _MAX_SPANS
            del _finished_spans[:trim]
            _trim_total += trim
            _flushed_upto = max(0, _flushed_upto - trim)
    _ensure_flusher()


def record_span(
    name: str,
    start_time: float,
    end_time: float,
    attributes: Optional[Dict[str, Any]] = None,
    context: Optional[Tuple[str, str, Optional[str]]] = None,
) -> None:
    """Record an already-timed span at the given (or current) context
    WITHOUT minting a new span id.  Used by the task executor: the
    context installed from TaskSpec.trace_parent is the task's span, and
    its id is what child tasks were told their parent is."""
    ctx = context if context is not None else _ctx.get()
    if ctx is None:
        return
    trace_id, span_id, parent = ctx
    _record_span(
        {
            "name": name,
            "trace_id": trace_id,
            "span_id": span_id,
            "parent_span_id": parent,
            "start_time": start_time,
            "end_time": end_time,
            "pid": os.getpid(),
            "attributes": attributes or {},
        }
    )


def record_event_span(
    name: str,
    start_time: float,
    end_time: float,
    attributes: Optional[Dict[str, Any]] = None,
) -> None:
    """Record an already-timed standalone event as its own root span
    (fresh trace id), regardless of any active context.  For events
    that happen on background threads with no caller to parent them —
    jax compiles, profile captures — so they still land in
    ``state.timeline()``."""
    _record_span(
        {
            "name": name,
            "trace_id": _new_trace_id(),
            "span_id": _new_span_id(),
            "parent_span_id": None,
            "start_time": start_time,
            "end_time": end_time,
            "pid": os.getpid(),
            "attributes": attributes or {},
        }
    )


def drain_spans() -> List[Dict[str, Any]]:
    """Pop and return this process's finished spans."""
    global _flushed_upto, _drain_epoch
    with _span_lock:
        out, _finished_spans[:] = list(_finished_spans), []
        _flushed_upto = 0
        _drain_epoch += 1
    return out


def flush() -> bool:
    """Ship spans recorded since the last flush to the GCS span table
    (mirrors util.metrics.flush; delivery goes through the same report
    channel so raylet/GCS processes export too).  Local consumers are
    unaffected: spans stay drainable until drain_spans() pops them.

    Each call ships at most CONFIG.span_flush_max_batch spans (ROADMAP
    PR-2 follow-up): sustained load produces a bounded report frame per
    interval instead of one unbounded ship-everything RPC; the remainder
    goes on the next interval (or the next explicit flush call).

    Delivery is at-least-once: a reply lost after the GCS applied the
    batch leaves the cursor behind and the batch is re-sent — readers
    dedupe by span_id (state._dedupe_spans)."""
    global _flushed_upto
    from ray_tpu._private.config import CONFIG

    try:
        max_batch = max(1, int(CONFIG.span_flush_max_batch))
    except Exception:
        max_batch = 2048
    with _span_lock:
        pending = _finished_spans[_flushed_upto : _flushed_upto + max_batch]
        mark = _flushed_upto + len(pending)
        base_trim = _trim_total
        base_epoch = _drain_epoch
    if not pending:
        return True
    from ray_tpu.util import metrics as _metrics

    payload = {
        "reporter": _metrics.reporter_id(),
        # Per-tenant accounting in the GCS span table (the raylet stamps
        # RAY_TPU_TENANT into worker environments).
        "tenant": os.environ.get("RAY_TPU_TENANT") or "default",
        "spans": pending,
    }
    if _metrics.report("span_report", payload):
        with _span_lock:
            if _drain_epoch == base_epoch:
                # Shift the snapshot index by whatever the ring trimmed
                # during the RPC so spans recorded mid-flight are not
                # marked as shipped.
                mark -= _trim_total - base_trim
                _flushed_upto = max(_flushed_upto, min(max(0, mark), len(_finished_spans)))
            # else: a drain cleared the log mid-flight; cursor already 0
        return True
    return False


def _ensure_flusher() -> None:
    global _flusher_started
    if _flusher_started:
        return
    with _span_lock:
        if _flusher_started:
            return
        _flusher_started = True

    def flush_loop():
        from ray_tpu._private.config import CONFIG

        while True:
            try:
                time.sleep(max(0.05, CONFIG.span_flush_interval_ms / 1000))
                flush()
            except Exception:
                pass

    threading.Thread(target=flush_loop, daemon=True, name="span-flush").start()
    import atexit

    atexit.register(lambda: _safe_flush())


def _safe_flush():
    try:
        # flush() ships one bounded batch per call; at exit, drain what
        # remains (bounded — the ring holds at most _MAX_SPANS).
        for _ in range(16):
            flush()
            with _span_lock:
                done = _flushed_upto >= len(_finished_spans)
            if done:
                break
    except Exception:
        pass


def use_opentelemetry(tracer=None) -> bool:
    """Bridge spans to an OpenTelemetry tracer if the SDK is available
    (reference: tracing_helper's use of opentelemetry.trace)."""
    global _otel_tracer
    if tracer is not None:
        _otel_tracer = tracer
        return True
    try:
        from opentelemetry import trace as otel_trace

        _otel_tracer = otel_trace.get_tracer("ray_tpu")
        return True
    except Exception:
        return False
