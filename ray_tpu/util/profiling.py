"""Driver-side profiling orchestration: attach the on-demand sampling
profiler to live processes, collect the dumps, and merge them into one
cluster profile (reference: `ray timeline` + py-spy attach workflows).

``ray_tpu.util.state.profile(target, duration_s)`` is the front door;
the dashboard's ``/api/profile`` drives the same orchestration with its
own GCS/raylet clients (no connected worker), so everything here is
parameterized by two callables:

- ``gcs_call(method, payload)``  — one RPC to the GCS
- ``node_call(address, method, payload)`` — one RPC to a raylet/worker

Targets resolve to ``(label, address-or-gcs)`` pairs; labels key the
merged flamegraph (``actor:<tenant>/<class>``, ``worker:<pid>``,
``raylet:<node>``, ``gcs``) so a cluster-wide capture reads per-actor,
per-tenant at the roots.
"""

from __future__ import annotations

import json
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ray_tpu._private import profiling as _prof
from ray_tpu._private.profiling import (  # re-exported: the public error surface
    ProfilerConflictError,
    ProfilerError,
    ProfilerSessionNotFound,
)

__all__ = [
    "ProfileResult",
    "ProfilerError",
    "ProfilerConflictError",
    "ProfilerSessionNotFound",
    "resolve_targets",
    "run_profile",
]

_GCS_TARGET = "__gcs__"


class ProfileResult:
    """Merged outcome of one orchestrated capture across N processes.

    ``profiles`` holds the per-process capture records (possibly
    partial — a target that died mid-capture contributes whatever it
    shipped before dying, or an ``errors`` entry); exports fold them
    into collapsed-stack text or speedscope JSON.
    """

    def __init__(
        self,
        profiles: List[Dict[str, Any]],
        errors: List[Dict[str, str]],
        shared: Optional[List[Dict[str, str]]] = None,
    ):
        self.profiles = profiles
        self.errors = errors
        # Targets whose process was already being captured under another
        # label (the head node co-hosts GCS + raylet in one process):
        # their samples arrive via that other capture, not an error.
        self.shared = shared or []

    @property
    def total_samples(self) -> int:
        return sum(p.get("sample_count", 0) for p in self.profiles)

    @property
    def complete(self) -> bool:
        return not self.errors

    def merged_samples(self) -> Dict[str, int]:
        """Cluster-wide folded stacks, rooted at each process label."""
        return _prof.merge_records(self.profiles)

    def collapsed(self) -> str:
        """Collapsed-stack text (flamegraph.pl / speedscope import)."""
        lines = [f"{k} {v}" for k, v in sorted(self.merged_samples().items())]
        return "\n".join(lines) + ("\n" if lines else "")

    def speedscope(self) -> Dict[str, Any]:
        return _prof.speedscope(self.profiles)

    def top_frames(self, n: int = 10) -> List[Tuple[str, int, float]]:
        return _prof.top_frames(self.profiles, n)

    def attribution(self, needle: str) -> float:
        """Fraction of all samples whose stack mentions ``needle`` —
        the acceptance probe ("&ge;80% of samples in the workload")."""
        total = hit = 0
        for stack, count in self.merged_samples().items():
            total += count
            if needle in stack:
                hit += count
        return (hit / total) if total else 0.0

    def save(self, path: str, fmt: str = "collapsed") -> str:
        """Write ``collapsed`` text or ``speedscope`` JSON to ``path``."""
        if fmt == "collapsed":
            body = self.collapsed()
        elif fmt == "speedscope":
            body = json.dumps(self.speedscope())
        else:
            raise ValueError(f"unknown profile format {fmt!r}")
        with open(path, "w") as f:
            f.write(body)
        return path

    def summary(self) -> Dict[str, Any]:
        return {
            "targets": [p.get("label") for p in self.profiles],
            "total_samples": self.total_samples,
            "errors": self.errors,
            "shared": self.shared,
            "top_frames": [
                {"frame": f, "samples": c, "fraction": round(fr, 4)}
                for f, c, fr in self.top_frames(10)
            ],
        }


# ----------------------------------------------------------------------
# target resolution
# ----------------------------------------------------------------------
def _actor_target(info: Dict[str, Any]) -> Tuple[str, str]:
    if not info:
        raise ValueError("no such actor")
    if info.get("state") != "ALIVE":
        raise ValueError(f"actor is {info.get('state')}, not ALIVE")
    addr = info.get("worker_address")
    if not addr:
        raise ValueError("actor's worker has no direct RPC endpoint")
    tenant = info.get("tenant") or "default"
    name = info.get("name") or info.get("class_name") or "actor"
    return (f"actor:{tenant}/{name}", addr)


def resolve_targets(
    target: Any,
    gcs_call: Callable[[str, Any], Any],
    include_workers: bool = True,
) -> List[Tuple[str, str]]:
    """Resolve ``target`` into ``[(label, address)]``; address
    ``__gcs__`` means "call the GCS itself".

    Accepted targets: an ``ActorHandle``; an actor id (hex str or
    ``ActorID``); a node id hex (profiles that raylet, plus its workers
    when ``include_workers``); ``"gcs"``; ``None``/``"cluster"`` for
    everything (GCS + every raylet + every worker).
    """
    from ray_tpu._private.ids import ActorID, NodeID

    # ActorHandle without importing the actor module up front.
    actor_id = None
    if hasattr(target, "_actor_id"):
        actor_id = target._actor_id
    elif isinstance(target, ActorID):
        actor_id = target

    if actor_id is not None:
        # A just-created actor may still be PENDING_CREATION; give it a
        # short window to come up rather than failing the attach.
        from ray_tpu._private import retry

        bo = retry.POLL.start(deadline_s=10.0)
        while True:
            info = gcs_call("get_actor_info", actor_id.binary())
            if info and info.get("state") in ("PENDING_CREATION", "RESTARTING"):
                delay = bo.next_delay()
                if delay is not None:
                    time.sleep(delay)
                    continue
            return [_actor_target(info)]

    if target == "gcs":
        return [("gcs", _GCS_TARGET)]

    if isinstance(target, NodeID):
        target = target.hex()

    if target not in (None, "", "cluster") and not isinstance(target, str):
        # An unrecognized TYPE must not silently widen to a cluster-wide
        # capture (which consumes the one-session slot in EVERY
        # process) — fail loudly like the unrecognized-string case.
        raise ValueError(f"unrecognized profile target {target!r}")

    info = gcs_call("get_cluster_info", None)
    nodes = {NodeID(n["node_id"]).hex(): n for n in info["nodes"].values()}

    if isinstance(target, str) and target in nodes:
        return _node_targets(nodes[target], target, include_workers)

    if isinstance(target, str) and target not in ("", "cluster"):
        # Hex actor id as a plain string.
        try:
            aid = ActorID(bytes.fromhex(target))
        except ValueError:
            raise ValueError(f"unrecognized profile target {target!r}") from None
        return [_actor_target(gcs_call("get_actor_info", aid.binary()))]

    # cluster-wide
    out: List[Tuple[str, str]] = [("gcs", _GCS_TARGET)]
    for hexid, n in sorted(nodes.items()):
        if n.get("state") not in ("ALIVE", "DRAINING"):
            continue
        out.extend(_node_targets(n, hexid, include_workers))
    return out


def _node_targets(node: Dict[str, Any], hexid: str, include_workers: bool):
    out = [(f"raylet:{hexid[:8]}", node["raylet_address"])]
    if include_workers:
        # Worker endpoints come from the raylet at capture time (the
        # orchestrator asks node_stats right before attaching).
        out.append((f"__workers_of__:{hexid[:8]}", node["raylet_address"]))
    return out


# ----------------------------------------------------------------------
# orchestration
# ----------------------------------------------------------------------
def run_profile(
    targets: List[Tuple[str, str]],
    gcs_call: Callable[..., Any],
    node_call: Callable[..., Any],
    duration_s: float = 5.0,
    hz: Optional[float] = None,
    mode: str = "wall",
    rpc_timeout_s: float = 10.0,
) -> ProfileResult:
    """Attach to every target, wait out the capture, dump, merge.

    Attach/dump RPCs are serial but carry ``rpc_timeout_s`` (not the
    120 s default): one wedged process — exactly the kind a cluster
    capture wants to look at — costs seconds per target, not minutes.

    Dies-mid-capture semantics: a target whose dump fails contributes
    an ``errors`` entry; if its process shipped a (partial or complete)
    record to the GCS profile table before dying, that record is
    recovered from there.  The rest of the targets are unaffected —
    the result is partial, never an exception.
    """
    # Floor: a negative/zero duration would attach samplers everywhere
    # and then die in time.sleep() below.  Ceiling: samplers clamp
    # themselves to profile_max_duration_s — sleeping longer than that
    # would block the caller past the capture window and silently
    # return a truncated profile.
    from ray_tpu._private.config import CONFIG

    try:
        max_duration = float(CONFIG.profile_max_duration_s)
    except Exception:  # noqa: BLE001
        max_duration = 600.0
    duration_s = min(max(0.05, float(duration_s)), max_duration)

    def call(addr: str, method: str, payload: Any):
        if addr == _GCS_TARGET:
            return gcs_call(method, payload, rpc_timeout_s)
        return node_call(addr, method, payload, rpc_timeout_s)

    expanded: List[Tuple[str, str]] = []
    errors: List[Dict[str, str]] = []
    for label, addr in targets:
        if label.startswith("__workers_of__:"):
            node_tag = label.split(":", 1)[1]
            try:
                stats = call(addr, "node_stats", {})
            except Exception as e:  # noqa: BLE001 — raylet gone: note and move on
                errors.append({"target": label, "error": f"{type(e).__name__}: {e}"})
                continue
            for w in stats.get("workers", []):
                waddr = w.get("direct_address")
                if not waddr or w.get("state") == "DEAD":
                    continue
                # Root labels key the merged flamegraph by actor/tenant
                # (no spaces — labels are collapsed-stack frames).
                tenant = w.get("tenant") or "default"
                if w.get("actor_id"):
                    wlabel = (
                        f"actor:{tenant}/{w['actor_id'][:8]}/pid{w.get('pid')}"
                    )
                else:
                    wlabel = f"worker:{tenant}/{node_tag}/pid{w.get('pid')}"
                expanded.append((wlabel, waddr))
        else:
            expanded.append((label, addr))

    started: List[Tuple[str, str, str]] = []  # (label, addr, session_id)
    shared: List[Dict[str, str]] = []
    for label, addr in expanded:
        payload = {
            "duration_s": duration_s,
            "hz": hz,
            "mode": mode,
            "label": label,
        }
        try:
            rep = call(addr, "profile_start", payload)
            started.append((label, addr, rep["session_id"]))
        except ProfilerConflictError as e:
            if e.session_id and e.session_id in {s[2] for s in started}:
                # Same process already attached by THIS capture under
                # another label (the head co-hosts GCS + its raylet):
                # its samples arrive via that session — a note, not a
                # failure.
                shared.append({"target": label, "session_id": e.session_id})
            else:
                # Someone else's live session owns this process: its
                # samples will NOT be in this result — surface it.
                errors.append(
                    {
                        "target": label,
                        "error": (
                            "profiler busy: another session "
                            f"({e.session_id or 'unknown'}) is attached to this "
                            "process"
                        ),
                    }
                )
        except Exception as e:  # noqa: BLE001 — dead target: partial capture
            errors.append({"target": label, "error": f"{type(e).__name__}: {e}"})

    if started:
        time.sleep(duration_s)

    profiles: List[Dict[str, Any]] = []
    for label, addr, sid in started:
        dump_payload = {"session_id": sid, "stop": True}
        try:
            rec = call(addr, "profile_dump", dump_payload)
            profiles.append(rec)
        except Exception as e:  # noqa: BLE001 — died mid-capture
            rec = _recover_from_gcs(gcs_call, sid)
            if rec is not None:
                profiles.append(rec)
            else:
                errors.append(
                    {
                        "target": label,
                        "session_id": sid,
                        "error": f"died mid-capture: {type(e).__name__}: {e}",
                    }
                )
    return ProfileResult(profiles, errors, shared)


def _recover_from_gcs(gcs_call, session_id: str) -> Optional[Dict[str, Any]]:
    """A dead target may still have shipped its record through the GCS
    report path (natural end of capture races the process kill)."""
    try:
        for rec in gcs_call("list_profiles", {"session_id": session_id}) or []:
            return rec
    except Exception:  # noqa: BLE001
        pass
    return None
