"""ActorPool (reference: python/ray/util/actor_pool.py)."""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, TypeVar

import ray_tpu

V = TypeVar("V")


class ActorPool:
    """Round-robins work over a fixed set of actors.

    pool = ActorPool([a1, a2])
    list(pool.map(lambda a, v: a.double.remote(v), [1, 2, 3, 4]))
    """

    def __init__(self, actors: Iterable[Any]):
        self._idle: List[Any] = list(actors)
        # future -> actor, only while the task is in flight
        self._future_to_actor = {}
        # submission index -> future, until the result is claimed
        self._index_to_future = {}
        self._next_task_index = 0
        self._next_return_index = 0
        self._claimed_unordered = set()
        self._pending_submits: List[tuple] = []

    def submit(self, fn: Callable[[Any, V], Any], value: V):
        if self._idle:
            actor = self._idle.pop()
            future = fn(actor, value)
            self._future_to_actor[future] = actor
            self._index_to_future[self._next_task_index] = future
            self._next_task_index += 1
        else:
            self._pending_submits.append((fn, value))

    def has_next(self) -> bool:
        return bool(self._index_to_future) or bool(self._pending_submits)

    def has_free(self) -> bool:
        return bool(self._idle) and not self._pending_submits

    def _flush_pending(self):
        while self._pending_submits and self._idle:
            fn, value = self._pending_submits.pop(0)
            self.submit(fn, value)

    def _wait_and_recycle(self, timeout: Optional[float]):
        """Block until any in-flight task finishes; free its actor."""
        ready, _ = ray_tpu.wait(list(self._future_to_actor), num_returns=1, timeout=timeout)
        if not ready:
            raise TimeoutError("timed out waiting for actor pool result")
        actor = self._future_to_actor.pop(ready[0])
        self._idle.append(actor)
        self._flush_pending()

    def get_next(self, timeout: Optional[float] = None) -> Any:
        """Next result in submission order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        while self._next_return_index in self._claimed_unordered:
            self._claimed_unordered.discard(self._next_return_index)
            self._next_return_index += 1
        idx = self._next_return_index
        self._flush_pending()
        while idx not in self._index_to_future:
            self._wait_and_recycle(timeout)
        future = self._index_to_future[idx]
        value = ray_tpu.get(future, timeout=timeout)
        del self._index_to_future[idx]
        self._next_return_index += 1
        actor = self._future_to_actor.pop(future, None)
        if actor is not None:
            self._idle.append(actor)
            self._flush_pending()
        return value

    def get_next_unordered(self, timeout: Optional[float] = None) -> Any:
        """Next result in completion order."""
        if not self.has_next():
            raise StopIteration("no more results to get")
        self._flush_pending()
        done = [i for i, f in self._index_to_future.items() if f not in self._future_to_actor]
        while not done:
            self._wait_and_recycle(timeout)
            done = [i for i, f in self._index_to_future.items() if f not in self._future_to_actor]
        idx = min(done)
        future = self._index_to_future.pop(idx)
        self._claimed_unordered.add(idx)
        return ray_tpu.get(future)

    def map(self, fn: Callable[[Any, V], Any], values: Iterable[V]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next()

    def map_unordered(self, fn: Callable[[Any, V], Any], values: Iterable[V]):
        for v in values:
            self.submit(fn, v)
        while self.has_next():
            yield self.get_next_unordered()

    def pop_idle(self):
        """Remove and return an idle actor, or None."""
        return self._idle.pop() if self._idle else None

    def push(self, actor):
        """Add an actor to the pool."""
        self._idle.append(actor)
        self._flush_pending()
