"""User-defined application metrics (reference: python/ray/util/metrics.py
Counter :137, Histogram :187, Gauge :262; export pipeline SURVEY.md §5 —
C++ opencensus → dashboard agent → Prometheus).

Here: each worker process batches metric records locally and flushes them
to the GCS metrics table (rpc `metrics_report`) on a background thread;
`ray_tpu.util.state.metrics()` and the dashboard's /metrics endpoint read
the aggregated view (Prometheus text format).
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

_FLUSH_INTERVAL_S = 2.0

_lock = threading.Lock()
_registry: Dict[Tuple[str, tuple], dict] = {}
_flusher_started = False


def _ensure_flusher():
    global _flusher_started
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True

    def flush_loop():
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            try:
                flush()
            except Exception:
                pass

    threading.Thread(target=flush_loop, daemon=True, name="metrics-flush").start()


def flush():
    """Push the current snapshot to GCS (no-op when not connected)."""
    from ray_tpu._private.worker import global_worker_maybe

    w = global_worker_maybe()
    if w is None or not w.connected or w.gcs_client is None:
        return
    with _lock:
        snapshot = [
            {
                "name": name,
                "tags": dict(tags),
                "type": rec["type"],
                "value": rec["value"] if rec["type"] != "histogram" else None,
                "buckets": rec.get("buckets"),
                "counts": list(rec.get("counts", [])),
                "sum": rec.get("sum", 0.0),
                "count": rec.get("count", 0),
                "description": rec.get("description", ""),
            }
            for (name, tags), rec in _registry.items()
        ]
    if snapshot:
        try:
            w.gcs_client.call(
                "metrics_report",
                {"worker_id": w.worker_id.binary() if w.worker_id else b"", "metrics": snapshot},
            )
        except Exception:
            pass


class _Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Optional[Tuple[str, ...]] = None):
        if not name:
            raise ValueError("metric name must be non-empty")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}
        _ensure_flusher()

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, tuple]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return (self._name, tuple(sorted(merged.items())))

    @property
    def info(self) -> dict:
        return {
            "name": self._name,
            "description": self._description,
            "tag_keys": self._tag_keys,
            "default_tags": self._default_tags,
        }


class Counter(_Metric):
    """Monotonically increasing (reference: util/metrics.py:137)."""

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc() requires value > 0")
        key = self._key(tags)
        with _lock:
            rec = _registry.setdefault(
                key, {"type": "counter", "value": 0.0, "description": self._description}
            )
            rec["value"] += value


class Gauge(_Metric):
    """Last-value-wins (reference: util/metrics.py:262)."""

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with _lock:
            _registry[key] = {"type": "gauge", "value": float(value), "description": self._description}


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class Histogram(_Metric):
    """Bucketed observations (reference: util/metrics.py:187)."""

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[List[float]] = None,
        tag_keys: Optional[Tuple[str, ...]] = None,
    ):
        super().__init__(name, description, tag_keys)
        bounds = boundaries if boundaries is not None else list(DEFAULT_BUCKETS)
        if any(b <= 0 for b in bounds) or sorted(bounds) != list(bounds):
            raise ValueError("histogram boundaries must be positive and sorted")
        self._boundaries = list(bounds)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        key = self._key(tags)
        with _lock:
            rec = _registry.setdefault(
                key,
                {
                    "type": "histogram",
                    "buckets": self._boundaries,
                    "counts": [0] * (len(self._boundaries) + 1),
                    "sum": 0.0,
                    "count": 0,
                    "description": self._description,
                },
            )
            i = 0
            while i < len(self._boundaries) and value > self._boundaries[i]:
                i += 1
            rec["counts"][i] += 1
            rec["sum"] += value
            rec["count"] += 1


def prometheus_text(metrics: List[dict]) -> str:
    """Render aggregated metric records in Prometheus exposition format."""
    lines = []
    by_name = defaultdict(list)
    for m in metrics:
        by_name[m["name"]].append(m)
    for name, group in sorted(by_name.items()):
        mtype = group[0]["type"]
        desc = group[0].get("description", "")
        lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {mtype if mtype != 'histogram' else 'histogram'}")
        for m in group:
            label = ",".join(f'{k}="{v}"' for k, v in sorted((m.get("tags") or {}).items()))
            label = "{" + label + "}" if label else ""
            if mtype == "histogram":
                cum = 0
                for bound, cnt in zip(m["buckets"] + [float("inf")], m["counts"]):
                    cum += cnt
                    b = "+Inf" if bound == float("inf") else repr(bound)
                    sep = "," if m.get("tags") else ""
                    tag_body = label[1:-1] if label else ""
                    lines.append(f'{name}_bucket{{{tag_body}{sep}le="{b}"}} {cum}')
                lines.append(f"{name}_sum{label} {m['sum']}")
                lines.append(f"{name}_count{label} {m['count']}")
            else:
                lines.append(f"{name}{label} {m['value']}")
    return "\n".join(lines) + "\n"
