"""User-defined application metrics (reference: python/ray/util/metrics.py
Counter :137, Histogram :187, Gauge :262; export pipeline SURVEY.md §5 —
C++ opencensus → dashboard agent → Prometheus).

Here: each worker process batches metric records locally and flushes them
to the GCS metrics table (rpc `metrics_report`) on a background thread;
`ray_tpu.util.state.metrics()` and the dashboard's /metrics endpoint read
the aggregated view (Prometheus text format).
"""

from __future__ import annotations

import atexit
import threading
import time
from bisect import bisect_left
from collections import defaultdict
from typing import Any, Callable, Dict, List, Optional, Tuple

_FLUSH_INTERVAL_S = 2.0

_lock = threading.Lock()
_registry: Dict[Tuple[str, tuple], dict] = {}
_flusher_started = False

# Processes without a connected worker (raylet, GCS, dashboard helpers)
# register a delivery channel instead: fn(method, payload) -> None ships
# one report RPC to the GCS by whatever transport the process owns.
_report_channel: Optional[Callable[[str, dict], Any]] = None
_reporter_id: bytes = b""


def set_report_channel(fn: Optional[Callable[[str, dict], Any]], reporter_id: bytes = b""):
    """Route metric/span reports through `fn(method, payload)` rather than
    the global worker's GCS client (raylet/GCS processes have no worker).
    reporter_id keys this process's snapshot in the GCS metrics table."""
    global _report_channel, _reporter_id
    _report_channel = fn
    _reporter_id = reporter_id


def report(method: str, payload: dict) -> bool:
    """Deliver one report RPC to the GCS via the registered channel or the
    connected global worker.  Returns False when neither is available."""
    if _report_channel is not None:
        try:
            _report_channel(method, payload)
            return True
        except Exception:
            return False
    from ray_tpu._private.worker import global_worker_maybe

    w = global_worker_maybe()
    if w is None or not w.connected or w.gcs_client is None:
        return False
    try:
        # Node attribution (no incarnation: workers are not fenced — the
        # GCS uses this to fold channel blocked/reattach counters into
        # the host node's gray-failure suspicion score).
        if getattr(w, "node_id", None) is not None:
            payload.setdefault("node_id", w.node_id.binary())
        # Bounded: this runs on flusher threads and at interpreter exit —
        # it must never park a dying worker on the full rpc call timeout.
        w.gcs_client.call(method, payload, timeout=10)
        return True
    except Exception:
        return False


def reporter_id() -> bytes:
    if _reporter_id:
        return _reporter_id
    from ray_tpu._private.worker import global_worker_maybe

    w = global_worker_maybe()
    if w is not None and w.worker_id is not None:
        return w.worker_id.binary()
    return b""


def _ensure_flusher():
    # Deferred to the first metric WRITE (not construction): importing a
    # module that defines metrics must not spawn threads — that breaks
    # fork-based process spawning and burns a thread in every process
    # that merely imports an instrumented module.
    global _flusher_started
    if _flusher_started:
        return
    with _lock:
        if _flusher_started:
            return
        _flusher_started = True

    def flush_loop():
        while True:
            time.sleep(_FLUSH_INTERVAL_S)
            try:
                flush()
            except Exception:
                pass

    threading.Thread(target=flush_loop, daemon=True, name="metrics-flush").start()
    # Short-lived workers die between flush ticks; push the final
    # snapshot (and any unflushed spans) on interpreter exit.
    atexit.register(_flush_at_exit)


def _flush_at_exit():
    try:
        flush()
    except Exception:
        pass
    try:
        from ray_tpu.util import tracing

        tracing.flush()
    except Exception:
        pass


def flush():
    """Push the current snapshot to GCS (no-op when not connected)."""
    with _lock:
        snapshot = [
            {
                "name": name,
                "tags": dict(tags),
                "type": rec["type"],
                "value": rec["value"] if rec["type"] != "histogram" else None,
                "buckets": rec.get("buckets"),
                "counts": list(rec.get("counts", [])),
                "sum": rec.get("sum", 0.0),
                "count": rec.get("count", 0),
                "description": rec.get("description", ""),
            }
            for (name, tags), rec in _registry.items()
        ]
    if snapshot:
        report("metrics_report", {"worker_id": reporter_id(), "metrics": snapshot})


class _Metric:
    def __init__(self, name: str, description: str = "", tag_keys: Optional[Tuple[str, ...]] = None):
        if not name:
            raise ValueError("metric name must be non-empty")
        self._name = name
        self._description = description
        self._tag_keys = tuple(tag_keys or ())
        self._default_tags: Dict[str, str] = {}

    def set_default_tags(self, tags: Dict[str, str]):
        self._default_tags = dict(tags)
        return self

    def _key(self, tags: Optional[Dict[str, str]]) -> Tuple[str, tuple]:
        merged = dict(self._default_tags)
        if tags:
            merged.update(tags)
        return (self._name, tuple(sorted(merged.items())))

    @property
    def info(self) -> dict:
        return {
            "name": self._name,
            "description": self._description,
            "tag_keys": self._tag_keys,
            "default_tags": self._default_tags,
        }


class _Bound:
    """A metric pre-resolved to one (name, tags) series: the per-event
    cost drops to lock + record update — no tag merge, no sorted() — so
    hot-path instrumentation (every RPC, every task) stays well under
    the <5% overhead budget.  The registry record is cached after first
    touch; records are never replaced for counters/histograms, so the
    cache cannot go stale."""

    __slots__ = ("_key", "_template", "_rec")

    def __init__(self, key: Tuple[str, tuple], template: dict):
        self._key = key
        self._template = template
        self._rec = None


class _BoundCounter(_Bound):
    def inc(self, value: float = 1.0):
        rec = self._rec
        with _lock:
            if rec is None:
                rec = self._rec = _registry.setdefault(self._key, dict(self._template))
            rec["value"] += value


class Counter(_Metric):
    """Monotonically increasing (reference: util/metrics.py:137)."""

    def inc(self, value: float = 1.0, tags: Optional[Dict[str, str]] = None):
        if value <= 0:
            raise ValueError("Counter.inc() requires value > 0")
        _ensure_flusher()
        key = self._key(tags)
        with _lock:
            rec = _registry.setdefault(
                key, {"type": "counter", "value": 0.0, "description": self._description}
            )
            rec["value"] += value

    def bound(self, tags: Optional[Dict[str, str]] = None) -> _BoundCounter:
        """Pre-resolve the tag set for hot-path increments.  The flusher
        check happens here, once, so per-event writes skip it."""
        _ensure_flusher()
        return _BoundCounter(
            self._key(tags),
            {"type": "counter", "value": 0.0, "description": self._description},
        )


class Gauge(_Metric):
    """Last-value-wins (reference: util/metrics.py:262)."""

    def set(self, value: float, tags: Optional[Dict[str, str]] = None):
        _ensure_flusher()
        key = self._key(tags)
        with _lock:
            _registry[key] = {"type": "gauge", "value": float(value), "description": self._description}


DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10)


class Histogram(_Metric):
    """Bucketed observations (reference: util/metrics.py:187)."""

    def __init__(
        self,
        name: str,
        description: str = "",
        boundaries: Optional[List[float]] = None,
        tag_keys: Optional[Tuple[str, ...]] = None,
    ):
        super().__init__(name, description, tag_keys)
        bounds = boundaries if boundaries is not None else list(DEFAULT_BUCKETS)
        if any(b <= 0 for b in bounds) or sorted(bounds) != list(bounds):
            raise ValueError("histogram boundaries must be positive and sorted")
        self._boundaries = list(bounds)

    def observe(self, value: float, tags: Optional[Dict[str, str]] = None):
        _ensure_flusher()
        key = self._key(tags)
        with _lock:
            rec = _registry.setdefault(key, self._template())
            i = bisect_left(self._boundaries, value)
            rec["counts"][i] += 1
            rec["sum"] += value
            rec["count"] += 1

    def _template(self) -> dict:
        return {
            "type": "histogram",
            "buckets": self._boundaries,
            "counts": [0] * (len(self._boundaries) + 1),
            "sum": 0.0,
            "count": 0,
            "description": self._description,
        }

    def bound(self, tags: Optional[Dict[str, str]] = None) -> "_BoundHistogram":
        """Pre-resolve the tag set for hot-path observations.  The
        flusher check happens here, once, so per-event writes skip it."""
        _ensure_flusher()
        return _BoundHistogram(self._key(tags), self._template(), self._boundaries)


class _BoundHistogram(_Bound):
    __slots__ = ("_boundaries",)

    def __init__(self, key, template, boundaries):
        super().__init__(key, template)
        self._boundaries = boundaries

    def observe(self, value: float):
        rec = self._rec
        with _lock:
            if rec is None:
                rec = self._rec = _registry.setdefault(self._key, dict(self._template))
                rec["counts"] = list(rec["counts"])  # never alias the template
            rec["counts"][bisect_left(self._boundaries, value)] += 1
            rec["sum"] += value
            rec["count"] += 1


def _escape_label_value(v: str) -> str:
    """Prometheus exposition escaping for label values: backslash, double
    quote, and newline (in that order — backslash first or the other
    escapes get double-escaped)."""
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text(metrics: List[dict]) -> str:
    """Render aggregated metric records in Prometheus exposition format."""
    lines = []
    by_name = defaultdict(list)
    for m in metrics:
        by_name[m["name"]].append(m)
    for name, group in sorted(by_name.items()):
        mtype = group[0]["type"]
        desc = _escape_help(group[0].get("description", ""))
        lines.append(f"# HELP {name} {desc}")
        lines.append(f"# TYPE {name} {mtype if mtype != 'histogram' else 'histogram'}")
        for m in group:
            label = ",".join(
                f'{k}="{_escape_label_value(v)}"'
                for k, v in sorted((m.get("tags") or {}).items())
            )
            label = "{" + label + "}" if label else ""
            if mtype == "histogram":
                cum = 0
                for bound, cnt in zip(m["buckets"] + [float("inf")], m["counts"]):
                    cum += cnt
                    b = "+Inf" if bound == float("inf") else repr(bound)
                    sep = "," if m.get("tags") else ""
                    tag_body = label[1:-1] if label else ""
                    lines.append(f'{name}_bucket{{{tag_body}{sep}le="{b}"}} {cum}')
                lines.append(f"{name}_sum{label} {m['sum']}")
                lines.append(f"{name}_count{label} {m['count']}")
            else:
                lines.append(f"{name}{label} {m['value']}")
    return "\n".join(lines) + "\n"
