"""multiprocessing.Pool API over ray_tpu tasks (reference:
python/ray/util/multiprocessing/pool.py:1 — Pool/apply/map/imap/starmap
with AsyncResult semantics).

Design: stateless calls run as plain remote tasks (not actor-bound like
the reference's actor pool) — the scheduler spreads them across the
cluster, `processes` caps in-flight submissions, and an `initializer`
runs lazily once per worker process via a module-level guard (matching
multiprocessing's per-process initializer contract)."""

from __future__ import annotations

from multiprocessing import TimeoutError  # re-export the stdlib type
from typing import Any, Callable, Iterable, List, Optional, Tuple

import ray_tpu

# per-worker-process initializer guard: (id of pool instance) -> done
_initialized_pools = set()


def _run_call(pool_id: str, initializer, initargs, fn, args, kwargs):
    if initializer is not None and pool_id not in _initialized_pools:
        initializer(*initargs)
        _initialized_pools.add(pool_id)
    return fn(*args, **(kwargs or {}))


def _run_chunk(pool_id: str, initializer, initargs, fn, chunk: List, star: bool):
    if initializer is not None and pool_id not in _initialized_pools:
        initializer(*initargs)
        _initialized_pools.add(pool_id)
    return [fn(*item) if star else fn(item) for item in chunk]


class AsyncResult:
    """multiprocessing.pool.AsyncResult over object refs.

    Callbacks fire ASYNCHRONOUSLY from a waiter thread when the result
    lands (multiprocessing semantics) — joblib's dispatch loop depends
    on completion callbacks arriving without anyone calling get()."""

    def __init__(self, refs: List, *, flatten: bool = False, callback=None,
                 error_callback=None, single: bool = False):
        import threading

        self._refs = refs
        self._flatten = flatten
        self._single = single
        self._callback = callback
        self._error_callback = error_callback
        self._done = False
        self._value: Any = None
        self._error: Optional[BaseException] = None
        self._lock = threading.RLock()  # callbacks may re-enter get()
        if callback is not None or error_callback is not None:
            threading.Thread(
                target=self._resolve_quiet, daemon=True, name="pool-async-result"
            ).start()

    def _resolve_quiet(self):
        try:
            self._resolve(None)
        except Exception:
            pass

    def _resolve(self, timeout: Optional[float]):
        # Wait OUTSIDE the lock: the background callback waiter holds an
        # untimed wait, and get(timeout=...) must still be able to raise
        # TimeoutError while it blocks (joblib's timeout retrieval
        # depends on this).
        if not self._done and timeout is not None:
            done, _ = ray_tpu.wait(
                list(self._refs), num_returns=len(self._refs), timeout=timeout
            )
            if len(done) < len(self._refs):
                raise TimeoutError()
        with self._lock:
            if self._done:
                return
            try:
                out = ray_tpu.get(self._refs)
                if self._flatten:
                    out = [x for chunk in out for x in chunk]
                self._value = out[0] if self._single else out
                # _done BEFORE the callback: a callback that re-enters
                # get() must see the settled state, not recurse
                self._done = True
                if self._callback is not None:
                    self._callback(self._value)
            except BaseException as e:  # noqa: BLE001 — stored, re-raised on get
                if not self._done:
                    self._error = e
                    self._done = True
                    if self._error_callback is not None:
                        self._error_callback(e)
                else:
                    raise  # callback itself raised: propagate

    def get(self, timeout: Optional[float] = None):
        self._resolve(timeout)
        with self._lock:
            if self._error is not None:
                raise self._error
            return self._value

    def wait(self, timeout: Optional[float] = None):
        try:
            ray_tpu.wait(list(self._refs), num_returns=len(self._refs), timeout=timeout)
        except Exception:
            pass

    def ready(self) -> bool:
        if self._done:
            return True
        done, _ = ray_tpu.wait(list(self._refs), num_returns=len(self._refs), timeout=0)
        return len(done) == len(self._refs)

    def successful(self) -> bool:
        if not self.ready():
            raise ValueError("not ready")
        self._resolve(None)
        with self._lock:
            return self._error is None


class Pool:
    """reference: util/multiprocessing/pool.py Pool."""

    def __init__(self, processes: Optional[int] = None, initializer: Optional[Callable] = None,
                 initargs: Tuple = (), ray_remote_args: Optional[dict] = None):
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        if processes is None:
            processes = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if processes < 1:
            raise ValueError("processes must be >= 1")
        self._processes = processes
        self._initializer = initializer
        self._initargs = initargs
        self._id = f"pool-{id(self)}-{ray_tpu.runtime_context.get_runtime_context().get_job_id()}"
        opts = dict(ray_remote_args or {})
        opts.setdefault("num_cpus", 1)
        self._call = ray_tpu.remote(**opts)(_run_call)
        self._chunk_task = ray_tpu.remote(**opts)(_run_chunk)
        self._closed = False

    # -- helpers ---------------------------------------------------------
    def _check_running(self):
        if self._closed:
            raise ValueError("Pool not running")

    def _chunks(self, iterable: Iterable, chunksize: Optional[int]) -> List[List]:
        items = list(iterable)
        if chunksize is None:
            chunksize = max(1, len(items) // (self._processes * 4) or 1)
        return [items[i : i + chunksize] for i in range(0, len(items), chunksize)]

    def _submit_chunks(self, fn, chunks: List[List], star: bool) -> List:
        """Submit with at most processes*2 chunks in flight (the
        reference bounds in-flight work the same way so huge maps don't
        flood the scheduler)."""
        refs, pending = [], []
        for chunk in chunks:
            if len(pending) >= self._processes * 2:
                _, pending = ray_tpu.wait(pending, num_returns=1)
            ref = self._chunk_task.remote(
                self._id, self._initializer, self._initargs, fn, chunk, star
            )
            refs.append(ref)
            pending.append(ref)
        return refs

    # -- API -------------------------------------------------------------
    def apply(self, fn, args: Tuple = (), kwargs: Optional[dict] = None):
        return self.apply_async(fn, args, kwargs).get()

    def apply_async(self, fn, args: Tuple = (), kwargs: Optional[dict] = None,
                    callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        ref = self._call.remote(
            self._id, self._initializer, self._initargs, fn, args, kwargs
        )
        return AsyncResult([ref], single=True, callback=callback,
                           error_callback=error_callback)

    def map(self, fn, iterable: Iterable, chunksize: Optional[int] = None) -> List:
        return self.map_async(fn, iterable, chunksize).get()

    def map_async(self, fn, iterable: Iterable, chunksize: Optional[int] = None,
                  callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        refs = self._submit_chunks(fn, self._chunks(iterable, chunksize), star=False)
        return AsyncResult(refs, flatten=True, callback=callback,
                           error_callback=error_callback)

    def starmap(self, fn, iterable: Iterable, chunksize: Optional[int] = None) -> List:
        return self.starmap_async(fn, iterable, chunksize).get()

    def starmap_async(self, fn, iterable: Iterable, chunksize: Optional[int] = None,
                      callback=None, error_callback=None) -> AsyncResult:
        self._check_running()
        refs = self._submit_chunks(fn, self._chunks(iterable, chunksize), star=True)
        return AsyncResult(refs, flatten=True, callback=callback,
                           error_callback=error_callback)

    def imap(self, fn, iterable: Iterable, chunksize: int = 1):
        """Ordered results iterator.  Submission happens EAGERLY at the
        call (multiprocessing semantics: imap kicks off the work even if
        the iterator is never consumed; only retrieval is lazy)."""
        self._check_running()
        refs = self._submit_chunks(fn, self._chunks(iterable, chunksize), star=False)

        def results():
            for ref in refs:
                yield from ray_tpu.get(ref)

        return results()

    def imap_unordered(self, fn, iterable: Iterable, chunksize: int = 1):
        """Completion-ordered results iterator (eager submission, as
        above)."""
        self._check_running()
        refs = self._submit_chunks(fn, self._chunks(iterable, chunksize), star=False)

        def results():
            pending = list(refs)
            while pending:
                done, pending = ray_tpu.wait(pending, num_returns=1)
                yield from ray_tpu.get(done[0])

        return results()

    def close(self):
        self._closed = True

    def terminate(self):
        self._closed = True

    def join(self):
        if not self._closed:
            raise ValueError("Pool is still running")

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.terminate()
