"""Drop-in multiprocessing.Pool over the cluster (reference:
python/ray/util/multiprocessing/pool.py — same public surface, tasks
instead of forked processes, so pools span nodes and survive worker
crashes via normal task retry)."""

from ray_tpu.util.multiprocessing.pool import AsyncResult, Pool, TimeoutError

__all__ = ["Pool", "AsyncResult", "TimeoutError"]
