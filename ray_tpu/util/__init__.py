"""ray_tpu.util — core extensions (reference: python/ray/util/)."""

from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    placement_group_table,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)

__all__ = [
    "PlacementGroup",
    "placement_group",
    "placement_group_table",
    "remove_placement_group",
    "NodeAffinitySchedulingStrategy",
    "PlacementGroupSchedulingStrategy",
    "ActorPool",
    "Queue",
    "list_named_actors",
]


def list_named_actors(all_namespaces: bool = False):
    """Currently alive named actors (reference: ray.util.list_named_actors):
    their names in the caller's namespace, or
    ``[{"namespace": ..., "name": ...}]`` across all namespaces."""
    from ray_tpu._private.worker import get_global_worker

    worker = get_global_worker()
    rows = worker.gcs_client.call(
        "list_named_actors", (bool(all_namespaces), worker.namespace)
    ) or []
    if all_namespaces:
        return rows
    return [r["name"] for r in rows]


def __getattr__(name):
    if name == "ActorPool":
        from ray_tpu.util.actor_pool import ActorPool

        return ActorPool
    if name == "Queue":
        from ray_tpu.util.queue import Queue

        return Queue
    if name in ("collective", "state", "metrics", "queue"):
        import importlib

        return importlib.import_module(f"ray_tpu.util.{name}")
    raise AttributeError(name)
