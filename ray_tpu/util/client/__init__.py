"""Ray Client: remote drivers over a thin RPC proxy (reference:
python/ray/util/client/ARCHITECTURE.md — a server that is itself a
normal driver, doing all bookkeeping for connected clients; the client
side holds stubs).

Here the same shape, minus gRPC: the head node runs a client server
process that is an ordinary ray_tpu driver; ``ray_tpu.init("ray://host:port")``
swaps the process-global worker for a :class:`ClientWorker` that
forwards the Worker interface (submit_task / create_actor /
submit_actor_task / get / put / wait / kill) over the framed-pickle RPC
— so `@ray_tpu.remote` functions, actor handles, and ObjectRefs work
unchanged on top of it.  Per-connection references are pinned
server-side and released when client refs die or the client
disconnects.
"""

from ray_tpu.util.client.worker import ClientWorker, connect

__all__ = ["ClientWorker", "connect"]
