"""Client-side worker shim (reference: util/client/worker.py Worker +
common.py Client* stubs).

Implements the slice of the Worker interface that the public API layer
(remote_function.py, actor.py, ray_tpu.get/put/wait) calls, forwarding
every operation to the cluster's client server.  Because the API layer
only talks to `get_global_worker()`, installing a ClientWorker makes
`@ray_tpu.remote`, actor handles, and ObjectRefs work unchanged from a
machine that is not part of the cluster.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ray_tpu._private import rpc, serialization
from ray_tpu._private.ids import ActorID, ObjectID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu.actor import ActorHandle


class _ClientRefCounter:
    """Stands in for ReferenceCounter: batches releases to the server so
    dead client refs unpin their server-side objects."""

    def __init__(self, client: "ClientWorker"):
        self._client = client
        self._counts: Dict[ObjectID, int] = {}
        self._lock = threading.Lock()
        self._to_release: List[bytes] = []

    def add_owned(self, object_id: ObjectID):
        with self._lock:
            self._counts[object_id] = self._counts.get(object_id, 0) + 1

    def remove_owned(self, object_id: ObjectID):
        batch = None
        with self._lock:
            c = self._counts.get(object_id)
            if c is None:
                return
            if c <= 1:
                del self._counts[object_id]
                self._to_release.append(object_id.binary())
                if len(self._to_release) >= 100:
                    batch, self._to_release = self._to_release, []
            else:
                self._counts[object_id] = c - 1
        if batch:
            self._client._release(batch)

    def mark_escaped(self, object_id: ObjectID):
        pass  # server-side pins hold the object

    def flush(self):
        with self._lock:
            batch, self._to_release = self._to_release, []
        if batch:
            self._client._release(batch)


class ClientWorker:
    """mode="client" stand-in for the in-cluster Worker."""

    def __init__(self, address: str, namespace: Optional[str] = None,
                 runtime_env: Optional[dict] = None):
        self.mode = "client"
        self.connected = True
        self._rpc = rpc.RpcClient(address)
        self.reference_counter = _ClientRefCounter(self)
        self.namespace = namespace or "default"
        self.session_info: dict = {}
        self._env_cache: Dict[str, dict] = {}
        info = self._rpc.call("client_cluster_info", None, timeout=30)
        self._num_nodes = info["num_nodes"]
        # The job runtime_env is packaged on THIS machine (local
        # working_dir/py_modules zip from the client's filesystem, like
        # the reference Ray Client's upload-from-remote-driver) and the
        # packages are shipped to the cluster's GCS KV via the server.
        self.job_runtime_env = self._prepare_env(runtime_env)

    def _prepare_env(self, raw: Optional[dict]) -> Optional[dict]:
        """Normalize a runtime_env CLIENT-side: zip local dirs from the
        client filesystem, upload packages through the server, return the
        gcs://-only normalized env safe to evaluate anywhere."""
        import json as _json

        from ray_tpu._private import runtime_env as runtime_env_mod

        if not raw:
            return None
        key = _json.dumps(raw, sort_keys=True, default=str)
        cached = self._env_cache.get(key)
        if cached is not None:
            return cached or None
        def _upload(uri, blob):
            # Content-addressed: skip shipping up to 200 MB over the WAN
            # when the cluster already holds this sha (reference client
            # checks package existence before upload).
            if not self._rpc.call("client_package_exists", uri, timeout=30):
                self._rpc.call("client_upload_package", (uri, blob), timeout=120)

        norm = runtime_env_mod.normalize_uploaded(raw, _upload)
        self._env_cache[key] = norm
        return norm or None

    # -- arg packing (values inline, refs by id) ------------------------
    def _pack_args(self, args: Tuple, kwargs: Dict) -> list:
        if kwargs:
            raise ValueError("kwargs are not supported over ray:// (pass positionally)")
        packed = []
        for a in args:
            if isinstance(a, ObjectRef):
                packed.append(("ref", a.id.binary()))
            else:
                packed.append(("v", serialization.serialize_to_bytes(a)))
        return packed

    def _refs(self, ids: List[bytes]) -> List[ObjectRef]:
        return [ObjectRef(ObjectID(i), owned=True) for i in ids]

    def _release(self, ids: List[bytes]):
        try:
            self._rpc.push("client_release", ids)
        except rpc.RpcError:
            pass

    # -- Worker interface ----------------------------------------------
    def put(self, value: Any) -> ObjectRef:
        oid = self._rpc.call("client_put", serialization.serialize_to_bytes(value))
        return ObjectRef(ObjectID(oid), owned=True)

    def get(self, refs: List[ObjectRef], timeout: Optional[float] = None) -> List[Any]:
        blobs = self._rpc.call(
            "client_get",
            ([r.id.binary() for r in refs], timeout),
            timeout=(timeout + 30) if timeout is not None else None,
        )
        return [serialization.deserialize(memoryview(b))[1] for b in blobs]

    def wait(self, refs, num_returns, timeout, fetch_local=True):
        ready_ids, not_ready_ids = self._rpc.call(
            "client_wait",
            ([r.id.binary() for r in refs], num_returns, timeout),
            timeout=(timeout + 30) if timeout is not None else None,
        )
        by_id = {r.id.binary(): r for r in refs}
        return [by_id[i] for i in ready_ids], [by_id[i] for i in not_ready_ids]

    def submit_task(self, fn_blob, name, args, kwargs, options: dict):
        if options.get("num_returns") == "streaming":
            raise ValueError("num_returns='streaming' is not supported over ray://")
        ids = self._rpc.call(
            "client_schedule",
            {
                "fn_blob": fn_blob,
                "name": name,
                "args": self._pack_args(args, kwargs),
                "options": _client_options(self, options),
            },
        )
        return self._refs(ids)

    def create_actor(self, cls_blob, class_name, args, kwargs, options: dict) -> ActorID:
        aid = self._rpc.call(
            "client_create_actor",
            {
                "cls_blob": cls_blob,
                "name": class_name,
                "args": self._pack_args(args, kwargs),
                "options": _client_options(self, options),
            },
        )
        return ActorID(aid)

    def submit_actor_task(self, actor_id, method_name, args, kwargs, options: dict):
        ids = self._rpc.call(
            "client_actor_call",
            {
                "actor_id": actor_id.binary(),
                "method": method_name,
                "args": self._pack_args(args, kwargs),
                # env + namespace are fixed at actor creation; plain
                # options keep the per-call hot path cheap.
                "options": _plain_options(options),
            },
        )
        return self._refs(ids)

    def kill_actor(self, actor_id, no_restart: bool = True):
        self._rpc.call("client_kill_actor", {"actor_id": actor_id.binary(), "no_restart": no_restart})

    def cancel_task(self, object_id, force: bool = False):
        self._rpc.call("client_cancel", {"id": object_id.binary(), "force": force})

    def fetch_function_blob(self, function_key: bytes) -> Optional[bytes]:
        """Registered function/class blob from the cluster's GCS (used by
        get_actor to rebuild a handle's method table client-side)."""
        return self._rpc.call("client_fetch_function", function_key)

    def get_named_actor(self, name, namespace):
        reply = self._rpc.call(
            "client_get_named_actor", (name, namespace or self.namespace)
        )
        if reply is None:
            raise ValueError(f"Failed to look up actor '{name}'")
        return reply

    def on_ref_serialized(self, object_id):
        pass  # pinned server-side

    def get_async(self, ref):  # pragma: no cover — parity stub
        raise NotImplementedError("await ref is not supported over ray://")

    def _check_connected(self):
        if not self.connected:
            raise RuntimeError("client disconnected")

    def disconnect(self):
        if not self.connected:
            return
        self.reference_counter.flush()
        self.connected = False
        try:
            self._rpc.close()
        except Exception:
            pass


def _plain_options(options: dict) -> dict:
    """Strip client-side-only / unserializable entries."""
    out = {}
    for k, v in options.items():
        if k in ("placement_group",) or k.startswith("_"):
            continue
        if k == "scheduling_strategy" and not isinstance(v, (str, type(None))):
            continue
        out[k] = v
    return out


def _client_options(worker: ClientWorker, options: dict) -> dict:
    """Resolve runtime_env and namespace on the CLIENT before shipping:
    local working_dir paths must mean the client's filesystem, and named
    actors must land in the client driver's namespace, not the client
    server's."""
    from ray_tpu._private import runtime_env as runtime_env_mod

    out = _plain_options(options)
    task_env = worker._prepare_env(out.get("runtime_env"))
    merged = runtime_env_mod.merge(worker.job_runtime_env, task_env)
    if merged:
        out["runtime_env"] = merged
    else:
        out.pop("runtime_env", None)
    if not out.get("namespace"):  # .options() ships namespace=None
        out["namespace"] = worker.namespace
    return out


def connect(address: str, namespace: Optional[str] = None,
            runtime_env: Optional[dict] = None) -> ClientWorker:
    """Install a ClientWorker as the process-global worker.  `address`
    is "ray://host:port" (or a raw tcp:/unix: RPC address)."""
    from ray_tpu._private import worker as worker_mod

    if address.startswith("ray://"):
        address = "tcp:" + address[len("ray://"):]
    client = ClientWorker(address, namespace=namespace, runtime_env=runtime_env)
    with worker_mod._worker_lock:
        worker_mod._global_worker = client
    return client
