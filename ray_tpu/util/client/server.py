"""Client server: an ordinary driver that executes API calls on behalf
of remote clients (reference: ray/util/client/server/server.py
RayletServicer — Schedule/Get/Put/Wait/Terminate + per-client refs).
"""

from __future__ import annotations

import logging
import threading
from collections import defaultdict
from typing import Any, Dict

from ray_tpu._private import rpc, serialization
from ray_tpu._private.ids import ActorID, ObjectID

logger = logging.getLogger(__name__)


class ClientServer:
    """Serves the client protocol; one instance per cluster, hosted in
    its own driver process (see server_main.py)."""

    def __init__(self, address: str, loop):
        from ray_tpu._private.worker import get_global_worker

        self.worker = get_global_worker()
        self.loop = loop
        self.server = rpc.RpcServer(self, address, loop)
        self.server.on_disconnect = self._on_disconnect
        # Pinned refs per client connection: conn id -> {id bytes: ObjectRef}
        self.refs: Dict[int, Dict[bytes, Any]] = defaultdict(dict)
        self.actors: Dict[int, set] = defaultdict(set)
        self._lock = threading.Lock()

    async def start(self):
        await self.server.start()
        logger.info("client server listening on %s", self.server.address)

    async def _on_disconnect(self, conn):
        """Client went away: release everything it owned (reference:
        server.py release_all)."""
        with self._lock:
            refs = self.refs.pop(id(conn), {})
            actors = self.actors.pop(id(conn), set())
        refs.clear()  # ObjectRef __del__ drops the pins
        for actor_id in actors:
            try:
                self.worker.kill_actor(ActorID(actor_id), no_restart=True)
            except Exception:  # noqa: BLE001 — named/detached may be shared
                pass

    # -- helpers --------------------------------------------------------
    def _pin(self, conn, refs):
        with self._lock:
            table = self.refs[id(conn)]
            for r in refs:
                table[r.id.binary()] = r

    def _resolve_args(self, conn, packed):
        """Client arg packing: ("v", blob) inline values, ("ref", id)."""
        args = []
        with self._lock:
            table = self.refs[id(conn)]
        for kind, payload in packed:
            if kind == "v":
                args.append(serialization.deserialize(memoryview(payload))[1])
            else:
                ref = table.get(payload)
                if ref is None:
                    from ray_tpu._private.object_ref import ObjectRef

                    ref = ObjectRef(ObjectID(payload), owned=False)
                args.append(ref)
        return args

    # -- protocol -------------------------------------------------------
    async def rpc_client_put(self, payload, conn):
        value = serialization.deserialize(memoryview(payload))[1]
        ref = self.worker.put(value)
        self._pin(conn, [ref])
        return ref.id.binary()

    async def rpc_client_get(self, payload, conn):
        ids, timeout = payload
        from ray_tpu._private.object_ref import ObjectRef

        refs = [ObjectRef(ObjectID(i), owned=False) for i in ids]
        import asyncio

        # Worker.get blocks: keep the server loop responsive.
        values = await asyncio.get_event_loop().run_in_executor(
            None, lambda: self.worker.get(refs, timeout)
        )
        return [serialization.serialize_to_bytes(v) for v in values]

    async def rpc_client_wait(self, payload, conn):
        ids, num_returns, timeout = payload
        from ray_tpu._private.object_ref import ObjectRef

        refs = [ObjectRef(ObjectID(i), owned=False) for i in ids]
        import asyncio

        ready, not_ready = await asyncio.get_event_loop().run_in_executor(
            None, lambda: self.worker.wait(refs, num_returns, timeout, True)
        )
        return ([r.id.binary() for r in ready], [r.id.binary() for r in not_ready])

    async def rpc_client_schedule(self, payload, conn):
        refs = self.worker.submit_task(
            payload["fn_blob"],
            payload["name"],
            tuple(self._resolve_args(conn, payload["args"])),
            {},
            payload["options"],
        )
        if not isinstance(refs, list):  # streaming unsupported over client
            raise ValueError("num_returns='streaming' is not supported over ray://")
        self._pin(conn, refs)
        return [r.id.binary() for r in refs]

    async def rpc_client_create_actor(self, payload, conn):
        actor_id = self.worker.create_actor(
            payload["cls_blob"],
            payload["name"],
            tuple(self._resolve_args(conn, payload["args"])),
            {},
            payload["options"],
        )
        with self._lock:
            if payload["options"].get("lifetime") != "detached":
                self.actors[id(conn)].add(actor_id.binary())
        return actor_id.binary()

    async def rpc_client_actor_call(self, payload, conn):
        refs = self.worker.submit_actor_task(
            ActorID(payload["actor_id"]),
            payload["method"],
            tuple(self._resolve_args(conn, payload["args"])),
            {},
            payload["options"],
        )
        if not isinstance(refs, list):
            raise ValueError("num_returns='streaming' is not supported over ray://")
        self._pin(conn, refs)
        return [r.id.binary() for r in refs]

    async def rpc_client_kill_actor(self, payload, conn):
        self.worker.kill_actor(ActorID(payload["actor_id"]), payload.get("no_restart", True))
        return True

    async def rpc_client_cancel(self, payload, conn):
        self.worker.cancel_task(ObjectID(payload["id"]), force=payload.get("force", False))
        return True

    async def rpc_client_get_named_actor(self, payload, conn):
        name, namespace = payload
        return self.worker.get_named_actor(name, namespace)

    async def push_client_release(self, payload, conn):
        with self._lock:
            table = self.refs.get(id(conn))
            if table:
                for i in payload:
                    table.pop(i, None)

    async def rpc_client_cluster_info(self, payload, conn):
        info = self.worker.gcs_client.call("get_cluster_info")
        return {"num_nodes": len(info["nodes"])}

    async def rpc_client_fetch_function(self, payload, conn):
        import asyncio

        from ray_tpu._private.worker import FUNCTION_KV_NS

        # Class blobs can be MBs: keep the blocking KV get off the loop.
        return await asyncio.get_event_loop().run_in_executor(
            None, lambda: self.worker.gcs_client.call("kv_get", (FUNCTION_KV_NS, payload))
        )

    async def rpc_client_package_exists(self, payload, conn):
        import asyncio

        from ray_tpu._private import runtime_env as runtime_env_mod

        key = payload[len(runtime_env_mod.URI_PREFIX):].encode()
        return await asyncio.get_event_loop().run_in_executor(
            None,
            lambda: bool(
                self.worker.gcs_client.call(
                    "kv_exists", (runtime_env_mod.KV_NS, key)
                )
            ),
        )

    async def rpc_client_upload_package(self, payload, conn):
        """Client-side-packaged runtime_env zip → the cluster's GCS KV
        (reference: ray client uploads working_dir from the remote
        driver's machine, not the server's)."""
        from ray_tpu._private import runtime_env as runtime_env_mod

        uri, blob = payload
        import asyncio

        # A working_dir zip can be hundreds of MB: keep the blocking KV
        # put off the server loop so other clients' RPCs keep flowing.
        await asyncio.get_event_loop().run_in_executor(
            None,
            lambda: runtime_env_mod.finish_uploads(self.worker.gcs_client, [(uri, blob)]),
        )
        return True
