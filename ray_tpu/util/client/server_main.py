"""Client server entrypoint: a driver process hosting ClientServer
(reference: util/client/server/__main__ — `ray start --head` launches
it next to the GCS)."""

from __future__ import annotations

import argparse
import asyncio
import logging
import signal


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-address", required=True)
    parser.add_argument("--listen", required=True, help="tcp:host:port or unix:path")
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO, format="[client-server %(asctime)s] %(message)s")

    import ray_tpu

    ray_tpu.init(address=args.gcs_address, log_to_driver=False)

    loop = asyncio.new_event_loop()
    asyncio.set_event_loop(loop)
    from ray_tpu.util.client.server import ClientServer

    server = ClientServer(args.listen, loop)
    stop = asyncio.Event()
    signal.signal(signal.SIGTERM, lambda *_: loop.call_soon_threadsafe(stop.set))
    signal.signal(signal.SIGINT, lambda *_: loop.call_soon_threadsafe(stop.set))

    async def run():
        await server.start()
        await stop.wait()

    loop.run_until_complete(run())
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
