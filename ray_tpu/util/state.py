"""State API (reference: python/ray/util/state/api.py:110 StateApiClient,
list_actors :781, summarize_tasks :1365; served by the dashboard state
head aggregating GCS + raylets).

Here the GCS is the aggregation point: actors/nodes/jobs/PGs come from
its tables; per-node task/object stats come from raylet `node_stats`;
task events come from the GCS task-event table fed by worker reports
(reference: core_worker/task_event_buffer.h → gcs_task_manager.h:86).
"""

from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from ray_tpu._private.ids import ActorID, NodeID
from ray_tpu._private.worker import get_global_worker


def _gcs():
    w = get_global_worker()
    if not w.connected:
        raise RuntimeError("ray_tpu is not initialized")
    return w.gcs_client


def list_nodes() -> List[Dict[str, Any]]:
    info = _gcs().call("get_cluster_info")
    return [
        {
            "node_id": NodeID(n["node_id"]).hex(),
            "state": n["state"],  # ALIVE | DRAINING | DEAD
            "is_head": n.get("is_head", False),
            "resources_total": n["resources_total"],
            "raylet_address": n["raylet_address"],
            "hostname": n.get("hostname", ""),
            "drain_reason": n.get("drain_reason"),
            "drain_deadline": n.get("drain_deadline", 0.0),
            "drain_complete": n.get("drain_complete", False),
        }
        for n in info["nodes"].values()
    ]


def list_actors(filters: Optional[List[tuple]] = None) -> List[Dict[str, Any]]:
    actors = _gcs().call("list_actors", None)
    out = []
    for a in actors:
        rec = {
            "actor_id": ActorID(a["actor_id"]).hex(),
            "state": a["state"],
            "class_name": a.get("class_name", ""),
            "name": a.get("name"),
            "node_id": NodeID(a["node_id"]).hex() if a.get("node_id") else None,
            "pid": a.get("pid", 0),
            "num_restarts": a.get("num_restarts", 0),
            "death_cause": a.get("death_cause"),
        }
        if _matches(rec, filters):
            out.append(rec)
    return out


def list_placement_groups() -> List[Dict[str, Any]]:
    return _gcs().call("list_placement_groups", None)


def list_jobs() -> List[Dict[str, Any]]:
    return _gcs().call("list_jobs", None)


def list_tasks(filters: Optional[List[tuple]] = None, limit: int = 10000) -> List[Dict[str, Any]]:
    events = _gcs().call("list_task_events", {"limit": limit})
    out = []
    for e in events:
        if _matches(e, filters):
            out.append(e)
    return out


def list_objects() -> List[Dict[str, Any]]:
    """Aggregate object-store stats over all raylets."""
    out = []
    for n in list_nodes():
        if n["state"] not in ("ALIVE", "DRAINING"):
            continue
        try:
            stats = _node_call(n["raylet_address"], "node_stats", {"include_objects": True})
        except Exception:
            continue
        for obj in stats.get("objects", []):
            obj["node_id"] = n["node_id"]
            out.append(obj)
    return out


def list_workers() -> List[Dict[str, Any]]:
    out = []
    for n in list_nodes():
        if n["state"] not in ("ALIVE", "DRAINING"):
            continue
        try:
            stats = _node_call(n["raylet_address"], "node_stats", {})
        except Exception:
            continue
        for w in stats.get("workers", []):
            w["node_id"] = n["node_id"]
            out.append(w)
    return out


def summarize_tasks() -> Dict[str, Any]:
    """Group task events by (name, state) (reference: summarize_tasks)."""
    tasks = list_tasks()
    summary: Dict[str, Dict[str, int]] = {}
    for t in tasks:
        name = t.get("name", "?")
        state = t.get("state", "?")
        summary.setdefault(name, {})
        summary[name][state] = summary[name].get(state, 0) + 1
    return {"node_count": len([n for n in list_nodes() if n["state"] == "ALIVE"]), "summary": summary}


def summarize_actors() -> Dict[str, Any]:
    actors = list_actors()
    summary: Dict[str, Dict[str, int]] = {}
    for a in actors:
        cls = a.get("class_name", "?")
        summary.setdefault(cls, {})
        summary[cls][a["state"]] = summary[cls].get(a["state"], 0) + 1
    return {"summary": summary}


def metrics() -> List[Dict[str, Any]]:
    """Aggregated user + system metric records from the GCS."""
    return _gcs().call("metrics_get", None)


def profile(
    target: Any = None,
    duration_s: float = 5.0,
    hz: Optional[float] = None,
    mode: str = "wall",
    include_workers: bool = True,
):
    """Attach the on-demand sampling profiler to a live actor, node,
    the GCS, or the whole cluster, and return the merged
    ``ProfileResult`` (collapsed-stack / speedscope exports, top-frame
    attribution — docs/profiling.md).

    ``target``: an ``ActorHandle`` / actor id, a node id hex, ``"gcs"``,
    or ``None``/``"cluster"`` for everything.  Blocks ~``duration_s``.
    A target that dies mid-capture yields a partial result with an
    ``errors`` entry, never an exception.
    """
    from ray_tpu.util import profiling as profiling_mod

    gcs_call = _gcs().call
    targets = profiling_mod.resolve_targets(
        target, gcs_call, include_workers=include_workers
    )
    return profiling_mod.run_profile(
        targets, gcs_call, _node_call, duration_s=duration_s, hz=hz, mode=mode
    )


def profiles(session_id: Optional[str] = None) -> List[Dict[str, Any]]:
    """Capture records in the GCS profile table (shipped by profiled
    processes at end of capture — survives the profiled process)."""
    payload = {"session_id": session_id} if session_id else None
    return _gcs().call("list_profiles", payload) or []


def _dedupe_spans(records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Span delivery to the GCS is at-least-once (a lost span_report
    reply re-sends the batch), so collapse duplicates by span_id —
    duplicate records are byte-identical, keep the first."""
    seen = set()
    out = []
    for s in records:
        sid = s.get("span_id")
        if sid is not None and sid in seen:
            continue
        if sid is not None:
            seen.add(sid)
        out.append(s)
    return out


def spans(limit: int = 100_000) -> List[Dict[str, Any]]:
    """Cluster-wide finished spans from the GCS span table.  The local
    process's unflushed spans are shipped first so a driver's root spans
    appear alongside the worker spans they parent."""
    from ray_tpu.util import tracing

    tracing.flush()
    return _dedupe_spans(_gcs().call("list_spans", {"limit": limit}) or [])


def traces(limit: int = 100_000) -> List[Dict[str, Any]]:
    """Spans grouped per trace (cluster-wide), newest-first: each entry
    carries the span tree of one distributed call graph."""
    return group_traces(spans(limit))


_DP_HOP_SPANS = ("channel.write", "channel.read", "channel.reattach")
# Queue-wait histogram bounds (seconds): log-spaced from 10µs to 10s.
_DP_QW_BOUNDS = (1e-5, 1e-4, 1e-3, 1e-2, 1e-1, 1.0, 10.0)


def _quantile(sorted_vals: List[float], q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


def build_dataplane(
    span_records: List[Dict[str, Any]],
    metric_records: List[Dict[str, Any]],
) -> Dict[str, Any]:
    """Pure merge of channel hop spans and ``channel_*`` counter
    aggregates into the live hot-path health view (shared by
    ``util.state.dataplane()`` and the dashboard's ``/api/dataplane``,
    which has no connected worker).

    Per-edge stats come from sampled ``channel.write`` / ``channel.read``
    / ``channel.reattach`` spans grouped by their ``path`` attribute
    (the channel endpoint is the edge identity); a ``channel.read``
    span's duration is the frame's queue wait, so each edge carries a
    queue-wait p50/p95/max plus a log-bucketed histogram.  Cluster-wide
    counters (every op, not just sampled ones) ride alongside from the
    GCS metric table."""
    edges: Dict[str, Dict[str, Any]] = {}
    for s in span_records:
        name = s.get("name")
        if name not in _DP_HOP_SPANS:
            continue
        attrs = s.get("attributes") or {}
        path = str(attrs.get("path", "?"))
        e = edges.get(path)
        if e is None:
            e = edges[path] = {
                "path": path,
                "kind": attrs.get("kind"),
                "writes": 0,
                "reads": 0,
                "reattaches": 0,
                "reattach_failures": 0,
                "last_epoch": None,
                "pids": set(),
                "_qw": [],
            }
        if attrs.get("kind"):
            e["kind"] = attrs["kind"]
        if s.get("pid") is not None:
            e["pids"].add(s["pid"])
        if name == "channel.write":
            e["writes"] += 1
        elif name == "channel.read":
            e["reads"] += 1
            qw = attrs.get("queue_wait_s")
            if isinstance(qw, (int, float)):
                e["_qw"].append(float(qw))
        else:  # channel.reattach
            e["reattaches"] += 1
            if attrs.get("result") != "ok":
                e["reattach_failures"] += 1
            if attrs.get("epoch") is not None:
                e["last_epoch"] = attrs["epoch"]
    out_edges = []
    for e in sorted(edges.values(), key=lambda e: e["path"]):
        qw = sorted(e.pop("_qw"))
        counts = [0] * (len(_DP_QW_BOUNDS) + 1)
        for v in qw:
            for i, b in enumerate(_DP_QW_BOUNDS):
                if v <= b:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
        e["pids"] = sorted(e["pids"], key=str)
        e["queue_wait"] = {
            "count": len(qw),
            "p50_s": _quantile(qw, 0.50),
            "p95_s": _quantile(qw, 0.95),
            "max_s": qw[-1] if qw else 0.0,
            "histogram": {"bounds_s": list(_DP_QW_BOUNDS), "counts": counts},
        }
        out_edges.append(e)
    counters: Dict[str, Any] = {}
    for m in metric_records:
        name = m.get("name", "")
        if not (name.startswith("channel_") or name.startswith("socket_channel_")):
            continue
        if m.get("type") != "counter":
            continue
        tags = m.get("tags") or {}
        if tags:
            sub = counters.setdefault(name, {})
            sub["|".join(f"{k}={v}" for k, v in sorted(tags.items()))] = m.get("value", 0)
        else:
            counters[name] = m.get("value", 0)
    return {"edges": out_edges, "counters": counters}


def dataplane(limit: int = 100_000) -> Dict[str, Any]:
    """Live dataplane health: per-channel-edge hop/queue-wait stats
    derived from sampled trace spans, merged with the cluster-wide
    ``channel_*`` counters (docs/observability.md, "Dataplane
    tracing")."""
    span_records = spans(limit)
    try:
        metric_records = metrics()
    except Exception:
        metric_records = []
    return build_dataplane(span_records, metric_records)


_CP_OVERLAP_SLACK_S = 1e-6  # clock-jitter tolerance between siblings


def critical_path(group: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """The longest dependency chain through one trace's span tree.

    Within each span, sequential (non-overlapping) children form a
    dependency chain — the chain is walked backwards from the
    last-finishing child, each link the latest-ending child that ends
    before the next link starts.  Each link expands recursively, so the
    result is the root-first flattening of the chain that bounds the
    trace's end-to-end latency.  For a serve request that reads
    ``serve.request -> serve.queue -> serve.prefill -> serve.decode``
    and attributes wall time across the three phases; for a task tree it
    names the slowest submit chain.

    Entries: ``{name, span_id, duration_s, depth, segment}`` —
    ``segment=True`` marks links whose time actually accrues to the path
    (links further expanded by their own children contribute through
    those children instead), so ``sum(duration_s where segment)`` is the
    path's latency decomposition without double counting.  Pure: shared
    by the state API and the dashboard.
    """
    by_id = {s["span_id"]: s for s in group if s.get("span_id")}
    children: Dict[str, List[Dict[str, Any]]] = {}
    roots: List[Dict[str, Any]] = []
    for s in group:
        parent = s.get("parent_span_id")
        if parent and parent in by_id and parent != s.get("span_id"):
            children.setdefault(parent, []).append(s)
        else:
            roots.append(s)
    if not roots:
        return []

    def dur(s) -> float:
        return max(0.0, (s.get("end_time") or 0.0) - (s.get("start_time") or 0.0))

    def entry(s, depth, segment) -> Dict[str, Any]:
        return {
            "name": s.get("name"),
            "span_id": s.get("span_id"),
            "duration_s": dur(s),
            "depth": depth,
            "segment": segment,
        }

    def sequential_chain(kids) -> List[Dict[str, Any]]:
        """Backwards greedy: last-finishing child, then the latest-ending
        child that ends before it starts, ... — returned in start order."""
        chain: List[Dict[str, Any]] = []
        remaining = sorted(kids, key=lambda s: s.get("end_time") or 0.0)
        cursor = None
        while remaining:
            nxt = None
            for k in reversed(remaining):
                if cursor is None or (k.get("end_time") or 0.0) <= cursor + _CP_OVERLAP_SLACK_S:
                    nxt = k
                    break
            if nxt is None:
                break
            chain.append(nxt)
            remaining.remove(nxt)
            cursor = nxt.get("start_time") or 0.0
        chain.reverse()
        return chain

    def expand(s, depth, seen) -> List[Dict[str, Any]]:
        sid = s.get("span_id")
        kids = [k for k in children.get(sid, []) if k.get("span_id") not in seen]
        if not kids:
            return [entry(s, depth, True)]
        seen = seen | {sid}
        out = [entry(s, depth, False)]
        for k in sequential_chain(kids):
            out.extend(expand(k, depth + 1, seen))
        return out

    def total(path) -> float:
        return sum(e["duration_s"] for e in path if e["segment"])

    best = max((expand(r, 0, frozenset()) for r in roots), key=total)
    return best


def group_traces(span_records: List[Dict[str, Any]]) -> List[Dict[str, Any]]:
    """Pure grouping of span records into per-trace summaries (shared by
    the state API and the dashboard, which has no connected worker)."""
    by_trace: Dict[str, List[Dict[str, Any]]] = {}
    for s in span_records:
        tid = s.get("trace_id")
        if tid:
            by_trace.setdefault(tid, []).append(s)
    out = []
    for tid, group in by_trace.items():
        group.sort(key=lambda s: s.get("start_time", 0.0))
        start = min(s.get("start_time", 0.0) for s in group)
        end = max(s.get("end_time", 0.0) for s in group)
        cpath = critical_path(group)
        out.append(
            {
                "trace_id": tid,
                "span_count": len(group),
                "pids": sorted({s.get("pid") for s in group if s.get("pid") is not None}),
                "start_time": start,
                "duration_s": max(0.0, end - start),
                "root_names": [s.get("name") for s in group if not s.get("parent_span_id")],
                "critical_path": cpath,
                "critical_path_s": sum(
                    e["duration_s"] for e in cpath if e["segment"]
                ),
                "spans": group,
            }
        )
    out.sort(key=lambda t: t["start_time"], reverse=True)
    return out


def build_chrome_trace(
    task_events: List[Dict[str, Any]], span_records: List[Dict[str, Any]]
) -> List[Dict[str, Any]]:
    """Merge GCS task events and cross-process spans into one
    Chrome-trace/Perfetto event list.  Spans keep their real (pid, tid)
    so Perfetto renders one track per process/thread, and carry
    trace_id/span_id/parent_span_id in args so the call tree is
    reconstructable across process boundaries."""
    trace: List[Dict[str, Any]] = []
    for e in task_events:
        start = e.get("start_time")
        end = e.get("end_time") or time.time()
        if start is None:
            continue
        trace.append(
            {
                "cat": "task",
                "name": e.get("name", "task"),
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(0.0, (end - start)) * 1e6,
                "pid": e.get("node_id", "node")[:8] if e.get("node_id") else "node",
                "tid": e.get("worker_id", "worker")[:8] if e.get("worker_id") else "worker",
                "args": {k: v for k, v in e.items() if isinstance(v, (str, int, float, bool))},
            }
        )
    span_pids = set()
    for s in span_records:
        start = s.get("start_time")
        if start is None:
            continue
        end = s.get("end_time") or start
        args = {
            "trace_id": s.get("trace_id"),
            "span_id": s.get("span_id"),
            "parent_span_id": s.get("parent_span_id"),
        }
        for k, v in (s.get("attributes") or {}).items():
            if isinstance(v, (str, int, float, bool)):
                args[k] = v
        pid = s.get("pid", 0)
        span_pids.add(pid)
        trace.append(
            {
                "cat": "span",
                "name": s.get("name", "span"),
                "ph": "X",
                "ts": start * 1e6,
                "dur": max(0.0, end - start) * 1e6,
                "pid": pid,
                "tid": s.get("tid", 0),
                "args": args,
            }
        )
    for pid in sorted(span_pids, key=str):
        trace.append(
            {
                "ph": "M",
                "cat": "__metadata",
                "name": "process_name",
                "pid": pid,
                "tid": 0,
                "args": {"name": f"pid {pid}"},
            }
        )
    return trace


def timeline(filename: Optional[str] = None, include_spans: bool = True) -> Optional[str]:
    """Chrome-trace (catapult) export of the cluster flight recorder:
    task events PLUS spans merged from every process (reference:
    `ray timeline`, GcsTaskManager → chrome://tracing format; open the
    output in Perfetto or chrome://tracing)."""
    events = _gcs().call("list_task_events", {"limit": 100000})
    span_records: List[Dict[str, Any]] = []
    if include_spans:
        try:
            span_records = spans()
        except Exception:
            span_records = []
    trace = build_chrome_trace(events, span_records)
    if filename is None:
        return json.dumps(trace)
    with open(filename, "w") as f:
        json.dump(trace, f)
    return filename


# ----------------------------------------------------------------------
def _matches(rec: Dict[str, Any], filters: Optional[List[tuple]]) -> bool:
    if not filters:
        return True
    for f in filters:
        key, op, value = f
        actual = rec.get(key)
        if op in ("=", "=="):
            if str(actual) != str(value):
                return False
        elif op == "!=":
            if str(actual) == str(value):
                return False
        else:
            raise ValueError(f"unsupported filter op {op!r}")
    return True


_node_clients: Dict[str, Any] = {}


def _node_call(address: str, method: str, payload: Any, timeout: Optional[float] = None):
    from ray_tpu._private import rpc

    client = _node_clients.get(address)
    if client is None or client.closed:
        # Re-dial closed cached clients (connection loss must not
        # permanently break this address — the target may be back).
        client = rpc.RpcClient(address)
        _node_clients[address] = client
    if timeout is None:  # unset: keep the config-default call timeout
        return client.call(method, payload)
    return client.call(method, payload, timeout=timeout)
