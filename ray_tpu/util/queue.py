"""Distributed queue backed by an async actor (reference:
python/ray/util/queue.py)."""

from __future__ import annotations

import asyncio
from typing import Any, List, Optional

import ray_tpu


class Empty(Exception):
    pass


class Full(Exception):
    pass


class _QueueActor:
    def __init__(self, maxsize: int):
        self.queue: asyncio.Queue = asyncio.Queue(maxsize)

    async def put(self, item, timeout: Optional[float] = None):
        if timeout is None:
            await self.queue.put(item)
            return True
        try:
            await asyncio.wait_for(self.queue.put(item), timeout)
            return True
        except asyncio.TimeoutError:
            return False

    async def get(self, timeout: Optional[float] = None):
        if timeout is None:
            return (True, await self.queue.get())
        try:
            return (True, await asyncio.wait_for(self.queue.get(), timeout))
        except asyncio.TimeoutError:
            return (False, None)

    def put_nowait(self, item) -> bool:
        try:
            self.queue.put_nowait(item)
            return True
        except asyncio.QueueFull:
            return False

    def get_nowait(self):
        try:
            return (True, self.queue.get_nowait())
        except asyncio.QueueEmpty:
            return (False, None)

    def qsize(self) -> int:
        return self.queue.qsize()

    def empty(self) -> bool:
        return self.queue.empty()

    def full(self) -> bool:
        return self.queue.full()


class Queue:
    """Multi-producer multi-consumer queue usable from any worker."""

    def __init__(self, maxsize: int = 0, actor_options: Optional[dict] = None):
        opts = actor_options or {"num_cpus": 0.1}
        self.maxsize = maxsize
        self.actor = ray_tpu.remote(**opts)(_QueueActor).remote(maxsize)

    def put(self, item: Any, block: bool = True, timeout: Optional[float] = None):
        if not block:
            if not ray_tpu.get(self.actor.put_nowait.remote(item)):
                raise Full
            return
        ok = ray_tpu.get(self.actor.put.remote(item, timeout))
        if not ok:
            raise Full

    def get(self, block: bool = True, timeout: Optional[float] = None) -> Any:
        if not block:
            ok, item = ray_tpu.get(self.actor.get_nowait.remote())
            if not ok:
                raise Empty
            return item
        ok, item = ray_tpu.get(self.actor.get.remote(timeout))
        if not ok:
            raise Empty
        return item

    def put_nowait(self, item: Any):
        self.put(item, block=False)

    def get_nowait(self) -> Any:
        return self.get(block=False)

    def put_async(self, item: Any):
        """Fire-and-forget put; returns the ObjectRef."""
        return self.actor.put.remote(item, None)

    def qsize(self) -> int:
        return ray_tpu.get(self.actor.qsize.remote())

    def empty(self) -> bool:
        return ray_tpu.get(self.actor.empty.remote())

    def full(self) -> bool:
        return ray_tpu.get(self.actor.full.remote())

    def shutdown(self):
        ray_tpu.kill(self.actor)
