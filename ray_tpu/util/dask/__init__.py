"""Dask-on-ray_tpu scheduler (reference: python/ray/util/dask/scheduler.py
ray_dask_get — a dask `get` function executing the task graph as remote
tasks with object refs flowing between them).

Usage with dask installed:

    import dask
    dask.config.set(scheduler=ray_dask_get)
    ddf.sum().compute()

The scheduler itself only needs the dask GRAPH PROTOCOL (a dict of
key -> task-tuple/literal, nested keys as arguments), so it is fully
functional — and hermetically tested — without the dask package: pass
any graph dict + keys to ``ray_dask_get`` directly."""

from ray_tpu.util.dask.scheduler import ray_dask_get

__all__ = ["ray_dask_get"]
