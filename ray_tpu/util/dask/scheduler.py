"""ray_dask_get: execute a dask task graph as remote tasks (reference:
python/ray/util/dask/scheduler.py:1 ray_dask_get + _rayify_task).

Graph protocol (dask spec, implemented directly so the dask package is
optional):

  * a graph is ``{key: computation}``
  * a computation is a TASK ``(callable, arg0, arg1, ...)``, a KEY of
    another graph entry, a literal, or a (possibly nested) list of
    computations
  * ``get(graph, keys)`` returns the materialized values for ``keys``

Each task becomes one remote task whose arguments are the upstream
OBJECT REFS — the runtime's scheduler resolves them, so independent
subtrees run in parallel and intermediates never round-trip through the
driver (same dataflow shape as the reference's scheduler)."""

from __future__ import annotations

from typing import Any, Dict, Hashable, List

import ray_tpu


def _is_task(c: Any) -> bool:
    return isinstance(c, tuple) and len(c) > 0 and callable(c[0])


def _toposort(dsk: Dict) -> List[Hashable]:
    seen: set = set()
    order: List[Hashable] = []

    def deps_of(c: Any, out: set):
        if _is_task(c):
            for a in c[1:]:
                deps_of(a, out)
        elif isinstance(c, list):
            for a in c:
                deps_of(a, out)
        elif isinstance(c, Hashable) and c in dsk:
            out.add(c)

    def visit(key, stack):
        if key in seen:
            return
        if key in stack:
            raise ValueError(f"cycle in dask graph at {key!r}")
        stack.add(key)
        d: set = set()
        deps_of(dsk[key], d)
        for dep in d:
            visit(dep, stack)
        stack.discard(key)
        seen.add(key)
        order.append(key)

    for key in dsk:
        visit(key, set())
    return order


def _execute_task(task, refs):
    """Runs INSIDE a remote task: refs arrive as materialized values;
    rebuild the computation with them substituted."""

    def build(c):
        if _is_task(c):
            fn, *args = c
            return fn(*[build(a) for a in args])
        if isinstance(c, list):
            return [build(a) for a in c]
        if isinstance(c, _Ref):
            return refs[c.index]
        return c

    return build(task)


class _Ref:
    """Placeholder marking where an upstream result plugs in."""

    def __init__(self, index: int):
        self.index = index


@ray_tpu.remote
def _dask_task(task, *refs):
    return _execute_task(task, list(refs))


def ray_dask_get(dsk: Dict, keys, **kwargs):
    """dask ``get`` entry point (pass to dask.config.set(scheduler=...))."""
    produced: Dict[Hashable, Any] = {}

    for key in _toposort(dsk):
        comp = dsk[key]
        if _is_task(comp) or isinstance(comp, list):
            # swap nested key references for _Ref placeholders + ref args
            ref_args: List[Any] = []

            def swap(c):
                if _is_task(c):
                    return (c[0],) + tuple(swap(a) for a in c[1:])
                if isinstance(c, list):
                    return [swap(a) for a in c]
                if isinstance(c, Hashable) and c in produced:
                    ref_args.append(produced[c])
                    return _Ref(len(ref_args) - 1)
                return c

            produced[key] = _dask_task.remote(swap(comp), *ref_args)
        elif isinstance(comp, Hashable) and comp in produced:
            produced[key] = produced[comp]
        else:
            produced[key] = ray_tpu.put(comp)

    def materialize(k):
        if isinstance(k, list):
            return [materialize(x) for x in k]
        return ray_tpu.get(produced[k])

    return materialize(list(keys) if isinstance(keys, list) else keys)
