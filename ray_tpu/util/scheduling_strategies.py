"""Scheduling strategies (reference:
python/ray/util/scheduling_strategies.py)."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional


@dataclass
class PlacementGroupSchedulingStrategy:
    placement_group: "object"
    placement_group_bundle_index: int = -1
    placement_group_capture_child_tasks: bool = False


@dataclass
class NodeAffinitySchedulingStrategy:
    node_id: str
    soft: bool = False


@dataclass
class NodeLabelSchedulingStrategy:
    """Run only on nodes whose labels contain every (key, value) in
    `hard` (reference: NodeLabelSchedulingStrategy + label scheduling
    policy).  Node labels come from `raylet --labels` / Cluster
    add_node(labels=...); TPU nodes get accelerator labels automatically
    (accelerators/tpu.py)."""

    hard: dict
    soft: Optional[dict] = None  # accepted for parity; hard rules decide


SPREAD = "SPREAD"
DEFAULT = "DEFAULT"
