"""RayBackend for joblib (reference: python/ray/util/joblib/ray_backend.py
— the reference plugs its multiprocessing Pool into joblib's
MultiprocessingBackend; here the seam is the same: a Pool-shaped object
whose apply_async ships each joblib BatchedCalls to a remote task)."""

from __future__ import annotations

from joblib._parallel_backends import MultiprocessingBackend

from ray_tpu.util.multiprocessing import Pool


class _PicklingPool(Pool):
    """joblib expects pool.apply_async(batch, callback=...) where batch
    is a zero-arg BatchedCalls; adapt to Pool's (fn, args) signature."""

    def apply_async(self, func, args=(), kwds=None, callback=None,
                    error_callback=None):
        # joblib passes the batch as `func` (zero-arg callable)
        return super().apply_async(
            _call_zero_arg, (func,), None, callback=callback,
            error_callback=error_callback,
        )


def _call_zero_arg(batch):
    return batch()


class RayBackend(MultiprocessingBackend):
    """parallel_backend("ray") — joblib batches run as cluster tasks."""

    supports_timeout = True

    def configure(self, n_jobs=1, parallel=None, prefer=None, require=None,
                  **memmapping_args):
        n_jobs = self.effective_n_jobs(n_jobs)
        # joblib's nesting guard: inner parallel regions run sequentially
        if n_jobs == 1:
            return 1
        self.parallel = parallel
        self._pool = _PicklingPool(processes=n_jobs)
        return n_jobs

    def effective_n_jobs(self, n_jobs):
        import ray_tpu

        if n_jobs == 0:
            raise ValueError("n_jobs == 0 has no meaning")
        if not ray_tpu.is_initialized():
            ray_tpu.init()
        cpus = max(1, int(ray_tpu.cluster_resources().get("CPU", 1)))
        if n_jobs is None or n_jobs < 0:
            return cpus
        return n_jobs

    def apply_async(self, func, callback=None):
        return self._pool.apply_async(func, callback=callback)

    def terminate(self):
        if getattr(self, "_pool", None) is not None:
            self._pool.terminate()
            self._pool = None

    def abort_everything(self, ensure_ready=True):
        self.terminate()
        if ensure_ready:
            self.configure(n_jobs=self.parallel.n_jobs, parallel=self.parallel)
