"""joblib backend over the cluster (reference:
python/ray/util/joblib/__init__.py register_ray +
ray_backend.py RayBackend).

    from ray_tpu.util.joblib import register_ray
    register_ray()
    with joblib.parallel_backend("ray"):
        GridSearchCV(...).fit(X, y)   # sklearn fans out as remote tasks

The backend subclasses joblib's MultiprocessingBackend surface at the
``apply_async`` seam: each joblib batch becomes one remote task, so
nested numpy/BLAS work runs in cluster workers instead of local forks.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


def register_ray() -> None:
    """Register the 'ray' parallel backend with joblib."""
    from joblib.parallel import register_parallel_backend

    from ray_tpu.util.joblib.ray_backend import RayBackend

    register_parallel_backend("ray", RayBackend)


__all__ = ["register_ray"]
