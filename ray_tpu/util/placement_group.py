"""Placement groups (reference: python/ray/util/placement_group.py;
GCS-side two-phase commit in gcs_placement_group_scheduler.h:283)."""

from __future__ import annotations

import time
from typing import Dict, List, Optional

from ray_tpu import exceptions
from ray_tpu._private.ids import PlacementGroupID
from ray_tpu._private.worker import get_global_worker


class PlacementGroup:
    def __init__(self, pg_id: PlacementGroupID, bundles: Optional[List[Dict[str, float]]] = None):
        self.id = pg_id
        self._bundles = bundles

    def ready(self):
        """ObjectRef-style readiness: returns self after blocking wait (the
        reference returns an ObjectRef of a marker task; here `wait()` is
        the canonical API and `ready()` is sugar over it)."""
        self.wait()
        return self

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        from ray_tpu._private import retry

        worker = get_global_worker()
        bo = retry.POLL.start(deadline_s=timeout_seconds)
        while True:
            info = worker.gcs_client.call("get_placement_group", self.id.binary())
            if info is None:
                raise exceptions.PlacementGroupSchedulingError("placement group removed")
            if info["state"] == "CREATED":
                return True
            if info["state"] == "REMOVED":
                raise exceptions.PlacementGroupSchedulingError("placement group removed")
            delay = bo.next_delay()
            if delay is None:
                return False
            time.sleep(delay)

    @property
    def bundle_specs(self) -> List[Dict[str, float]]:
        if self._bundles is None:
            worker = get_global_worker()
            info = worker.gcs_client.call("get_placement_group", self.id.binary())
            self._bundles = [b["resources"] for b in info["bundles"]] if info else []
        return self._bundles

    @property
    def bundle_count(self) -> int:
        return len(self.bundle_specs)

    def __reduce__(self):
        return (PlacementGroup, (self.id, self._bundles))


def placement_group(
    bundles: List[Dict[str, float]],
    strategy: str = "PACK",
    name: Optional[str] = None,
    lifetime: Optional[str] = None,
) -> PlacementGroup:
    if strategy not in ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD"):
        raise ValueError(f"invalid placement group strategy {strategy}")
    if not bundles:
        raise ValueError("placement group requires at least one bundle")
    for b in bundles:
        if not b or all(v == 0 for v in b.values()):
            raise ValueError("placement group bundles must request resources")
    worker = get_global_worker()
    pg_id = PlacementGroupID.from_random()
    worker.gcs_client.call(
        "create_placement_group",
        {
            "pg_id": pg_id.binary(),
            "bundles": [dict(b) for b in bundles],
            "strategy": strategy,
            "name": name,
            "lifetime": lifetime,
        },
    )
    return PlacementGroup(pg_id, [dict(b) for b in bundles])


def remove_placement_group(pg: PlacementGroup):
    worker = get_global_worker()
    worker.gcs_client.call("remove_placement_group", pg.id.binary())


def get_placement_group_state(pg: PlacementGroup) -> Optional[dict]:
    worker = get_global_worker()
    return worker.gcs_client.call("get_placement_group", pg.id.binary())


def placement_group_table() -> List[dict]:
    worker = get_global_worker()
    return worker.gcs_client.call("list_placement_groups", None)
