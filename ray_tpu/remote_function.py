"""@ray_tpu.remote on functions (reference: python/ray/remote_function.py:41
RemoteFunction; _remote() :303 pickles to the GCS function table and builds
a TaskSpec)."""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

from ray_tpu._private import serialization
from ray_tpu._private.worker import get_global_worker

_DEFAULT_OPTIONS = dict(
    num_cpus=None,
    num_gpus=None,
    num_tpus=None,
    memory=None,
    resources=None,
    num_returns=1,
    max_retries=None,
    retry_exceptions=False,
    scheduling_strategy=None,
    placement_group=None,
    placement_group_bundle_index=-1,
    runtime_env=None,
    name=None,
)


class RemoteFunction:
    def __init__(self, function, options: Optional[Dict[str, Any]] = None):
        self._function = function
        self._options = dict(_DEFAULT_OPTIONS)
        if options:
            self._options.update(options)
        self._function_blob: Optional[bytes] = None
        self._name = f"{function.__module__}.{function.__qualname__}"
        functools.update_wrapper(self, function)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._name}' cannot be called directly. "
            f"Use '{self._function.__name__}.remote()' instead."
        )

    def options(self, **options) -> "RemoteFunction":
        new = dict(self._options)
        new.update(options)
        rf = RemoteFunction(self._function, new)
        rf._function_blob = self._function_blob
        return rf

    def _blob(self) -> bytes:
        if self._function_blob is None:
            self._function_blob = serialization.dumps_function(self._function)
        return self._function_blob

    def remote(self, *args, **kwargs):
        worker = get_global_worker()
        opts = dict(self._options)
        if opts.get("max_retries") is None:
            opts.pop("max_retries")
        refs = worker.submit_task(
            self._blob(), opts.get("name") or self._name, args, kwargs, opts
        )
        if self._options["num_returns"] == "streaming":
            return refs  # an ObjectRefGenerator
        if self._options["num_returns"] == 1:
            return refs[0]
        return refs

    @property
    def bind(self):
        from ray_tpu.dag import bind_function

        return bind_function(self)
