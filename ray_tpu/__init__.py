"""ray_tpu — a TPU-native distributed compute framework.

Ray-class capabilities (tasks, actors, objects, placement groups, Train /
Data / Tune / RLlib libraries) designed TPU-first: collectives run inside
jitted XLA programs over ICI, the scheduler understands TPU slice
topology, and the AI libraries are JAX-native.

Public API parity target: reference python/ray/__init__.py
(init/remote/get/put/wait/kill/get_actor/...).

The core never imports jax — device work only happens in library code
(ray_tpu.train, ray_tpu.models, ...) inside worker processes.
"""

from __future__ import annotations

import atexit
import os
import threading
from typing import Any, List, Optional, Sequence, Union

from ray_tpu import exceptions
from ray_tpu._private.config import CONFIG
from ray_tpu._private.ids import ActorID, JobID, NodeID, ObjectID, TaskID, WorkerID
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.streaming import ObjectRefGenerator
from ray_tpu._private.worker import get_global_worker, global_worker_maybe
from ray_tpu.actor import ActorClass, ActorHandle, method
from ray_tpu.remote_function import RemoteFunction
from ray_tpu.runtime_context import get_runtime_context

__version__ = "0.1.0"

__all__ = [
    "init",
    "shutdown",
    "is_initialized",
    "remote",
    "get",
    "put",
    "wait",
    "kill",
    "cancel",
    "get_actor",
    "method",
    "nodes",
    "cluster_resources",
    "available_resources",
    "get_runtime_context",
    "ObjectRef",
    "ObjectRefGenerator",
    "ActorHandle",
    "exceptions",
    "__version__",
]

_init_lock = threading.RLock()
_node_processes = None  # NodeProcesses if this driver started the cluster


def init(
    address: Optional[str] = None,
    *,
    num_cpus: Optional[int] = None,
    num_tpus: Optional[int] = None,
    resources: Optional[dict] = None,
    object_store_memory: Optional[int] = None,
    namespace: Optional[str] = None,
    ignore_reinit_error: bool = False,
    log_to_driver: bool = True,
    runtime_env: Optional[dict] = None,
    tenant: Optional[str] = None,
    priority: Optional[int] = None,
    _system_config: Optional[dict] = None,
    **kwargs,
):
    """Start a new cluster (or connect to an existing one) and connect this
    process as a driver (reference: python/ray/_private/worker.py:1270)."""
    global _node_processes
    from ray_tpu._private import node as node_mod

    if address and address.startswith("ray://"):
        # Remote-driver (Ray Client) mode: swap in a ClientWorker that
        # proxies the Worker interface to the cluster's client server —
        # the rest of the API layer works unchanged on top of it
        # (reference: util/client/ARCHITECTURE.md).  namespace and
        # runtime_env are honored (packaged client-side); cluster-shaping
        # args are meaningless from a remote driver and rejected rather
        # than silently dropped.
        unsupported = {
            "num_cpus": num_cpus,
            "num_tpus": num_tpus,
            "resources": resources,
            "object_store_memory": object_store_memory,
            "_system_config": _system_config,
            # Tenant identity binds at the client server's driver
            # connection; a remote driver can't claim one yet.
            "tenant": tenant,
            "priority": priority,
        }
        bad = sorted(k for k, v in unsupported.items() if v is not None)
        bad += sorted(kwargs)  # unknown args, even explicit None
        if bad:
            raise ValueError(
                f"init(address='ray://...') does not support {bad}: a remote "
                "driver cannot reconfigure the cluster it connects to"
            )
        # log_to_driver: there is no log streaming over ray://, so False
        # (the only honorable value) is accepted as a no-op.
        with _init_lock:
            existing = global_worker_maybe()
            if existing is not None and existing.connected:
                if ignore_reinit_error:
                    return ClientContext(existing, address)
                raise RuntimeError(
                    "ray_tpu.init() called twice; pass ignore_reinit_error=True to ignore."
                )
            from ray_tpu.util.client import connect as _client_connect

            client = _client_connect(address, namespace=namespace, runtime_env=runtime_env)
        return ClientContext(client, address)

    with _init_lock:
        worker = get_global_worker()
        if worker.connected:
            if ignore_reinit_error:
                return RayContext(worker)
            raise RuntimeError("ray_tpu.init() called twice; pass ignore_reinit_error=True to ignore.")
        CONFIG.initialize(_system_config)
        if object_store_memory is not None:
            CONFIG._overrides["object_store_memory_cap"] = int(object_store_memory)
        CONFIG._overrides["log_to_driver"] = bool(log_to_driver)

        if address is None and os.environ.get("RAY_TPU_ADDRESS"):
            address = os.environ["RAY_TPU_ADDRESS"]
        if address == "auto":
            try:
                with open(node_mod.CLUSTER_ADDRESS_FILE) as f:
                    address = f.read().strip()
            except OSError:
                raise ConnectionError(
                    "address='auto' but no running cluster found. Start one with "
                    "`ray_tpu start --head` or call init() with no address."
                )

        if address is None:
            procs = node_mod.start_head(
                num_cpus=num_cpus, num_tpus=num_tpus, resources=resources
            )
            _node_processes = procs
            gcs_address = procs.gcs_address
            raylet_address = procs.raylet_address
        else:
            gcs_address = address
            raylet_address = node_mod.head_raylet_address(gcs_address)

        # Normalize the job-level runtime env before connecting (local
        # dirs become content-addressed gcs:// URIs); the packages are
        # uploaded right after the GCS connection exists, before any task
        # can be submitted (reference: runtime_env/working_dir.py
        # upload_package_if_needed).
        from ray_tpu._private import runtime_env as _renv

        norm_env, _uploads = _renv.prepare(runtime_env)
        # Multi-tenant job plane: every job carries a tenant (isolation/
        # accounting domain) and a priority class.  The job-submission
        # plane (dashboard job manager) passes them via env so submitted
        # entrypoints inherit without code changes.
        if tenant is None:
            tenant = os.environ.get("RAY_TPU_TENANT") or None
        if priority is None and os.environ.get("RAY_TPU_PRIORITY"):
            try:
                priority = int(os.environ["RAY_TPU_PRIORITY"])
            except ValueError:
                priority = None
        worker.connect_driver(
            gcs_address,
            raylet_address,
            namespace,
            {
                "namespace": namespace or "",
                "runtime_env": norm_env or {},
                "tenant": tenant or "default",
                # None = unset: the GCS applies the tenant's registered
                # default priority; an explicit value always wins.
                "priority": int(priority) if priority is not None else None,
            },
        )
        _renv.finish_uploads(worker.gcs_client, _uploads)
        worker.job_runtime_env = norm_env
        return RayContext(worker)


class ClientContext:
    """Returned by init("ray://..."); mirrors RayContext's surface."""

    def __init__(self, client, address: str):
        self._client = client
        self.address_info = {"address": address, "mode": "client"}

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()

    def disconnect(self):
        self._client.disconnect()
        # Drop the shim so a later in-cluster init() builds a real Worker.
        from ray_tpu._private import worker as worker_mod

        with worker_mod._worker_lock:
            if worker_mod._global_worker is self._client:
                worker_mod._global_worker = None


class RayContext:
    def __init__(self, worker):
        self._worker = worker
        self.address_info = {
            "gcs_address": worker.gcs_client.address if worker.gcs_client else None,
            "raylet_address": worker.raylet_client.address if worker.raylet_client else None,
            "node_id": worker.node_id.hex() if worker.node_id else None,
            "session_dir": worker.session_info.get("session_dir"),
            "webui_url": worker.session_info.get("dashboard_url"),
        }

    @property
    def dashboard_url(self):
        return self.address_info.get("webui_url")

    def __enter__(self):
        return self

    def __exit__(self, *a):
        shutdown()

    def __getitem__(self, key):
        return self.address_info[key]


def shutdown():
    global _node_processes
    with _init_lock:
        worker = global_worker_maybe()
        if worker is not None and worker.connected:
            worker.disconnect()
        if getattr(worker, "mode", None) == "client":
            # Drop the client shim so a later in-cluster init() builds a
            # real Worker.
            from ray_tpu._private import worker as worker_mod

            with worker_mod._worker_lock:
                worker_mod._global_worker = None
        if _node_processes is not None:
            _node_processes.terminate()
            _node_processes = None


atexit.register(shutdown)


def is_initialized() -> bool:
    w = global_worker_maybe()
    return w is not None and w.connected


def remote(*args, **kwargs):
    """@ray_tpu.remote decorator for functions and classes
    (reference: python/ray/_private/worker.py:3330)."""

    def make(target):
        import inspect

        if inspect.isclass(target):
            return ActorClass(target, kwargs or None)
        return RemoteFunction(target, kwargs or None)

    if len(args) == 1 and not kwargs and callable(args[0]):
        return make(args[0])
    if args:
        raise TypeError("@remote takes keyword arguments only, e.g. @remote(num_cpus=2)")
    return make


def put(value: Any) -> ObjectRef:
    return get_global_worker().put(value)


def get(refs: Union[ObjectRef, Sequence[ObjectRef]], *, timeout: Optional[float] = None):
    worker = get_global_worker()
    if isinstance(refs, ObjectRef):
        return worker.get([refs], timeout)[0]
    from ray_tpu.dag import CompiledDAGRef

    if isinstance(refs, CompiledDAGRef):
        return refs.get(timeout)  # None = wait forever, like ObjectRefs
    channel_get = getattr(refs, "__channel_get__", None)
    if channel_get is not None:
        # Dataplane futures (e.g. serve's ChannelFuture) resolve like
        # refs so await paths need no transport awareness.
        return channel_get(timeout)
    if not isinstance(refs, (list, tuple)):
        raise TypeError(f"ray_tpu.get takes an ObjectRef or a list of them, got {type(refs)}")
    for r in refs:
        if not isinstance(r, ObjectRef):
            raise TypeError(f"ray_tpu.get list must contain only ObjectRefs, got {type(r)}")
    return worker.get(list(refs), timeout)


def wait(
    refs: Sequence[ObjectRef],
    *,
    num_returns: int = 1,
    timeout: Optional[float] = None,
    fetch_local: bool = True,
):
    if isinstance(refs, ObjectRef):
        raise TypeError("ray_tpu.wait takes a list of ObjectRefs")
    if num_returns <= 0 or num_returns > len(refs):
        raise ValueError(f"num_returns must be in [1, {len(refs)}]")
    return get_global_worker().wait(list(refs), num_returns, timeout, fetch_local)


def kill(actor: ActorHandle, *, no_restart: bool = True):
    if not isinstance(actor, ActorHandle):
        raise TypeError("ray_tpu.kill() only works on actor handles; use cancel() for tasks")
    get_global_worker().kill_actor(actor._id, no_restart)


def cancel(ref: ObjectRef, *, force: bool = False, recursive: bool = True):
    """Cancel the task that produces `ref` (reference: core_worker.cc
    CancelTask).  Queued tasks are dropped; a running task gets
    TaskCancelledError raised inside it (force=True kills its worker
    process instead).  Cancelled tasks are never retried; a task that
    already finished is unaffected.  `recursive` is accepted for API
    compatibility (child-task tracking is not implemented — children
    keep running)."""
    worker = get_global_worker()
    worker.cancel_task(ref.id, force=force)


def get_actor(name: str, namespace: Optional[str] = None) -> ActorHandle:
    from ray_tpu.actor import get_actor_handle_from_spec

    worker = get_global_worker()
    reply = worker.get_named_actor(name, namespace)
    return get_actor_handle_from_spec(ActorID(reply["actor_id"]), reply["spec"])


def nodes() -> List[dict]:
    worker = get_global_worker()
    info = worker.gcs_client.call("get_cluster_info")
    out = []
    for n in info["nodes"].values():
        out.append(
            {
                "NodeID": NodeID(n["node_id"]).hex(),
                # DRAINING/SUSPECT/QUARANTINED nodes are still up (paying
                # out a notice or degraded-but-serving); State carries
                # the distinction.
                "Alive": n["state"] in ("ALIVE", "SUSPECT", "DRAINING", "QUARANTINED"),
                "State": n["state"],
                "DrainReason": n.get("drain_reason"),
                "Resources": n["resources_total"],
                "RayletAddress": n["raylet_address"],
                "IsHead": n.get("is_head", False),
                "Hostname": n.get("hostname", ""),
                "Labels": n.get("labels", {}),
            }
        )
    return out


def cluster_resources() -> dict:
    return get_global_worker().gcs_client.call("cluster_resources")


def available_resources() -> dict:
    return get_global_worker().gcs_client.call("available_resources")


def timeline(filename: Optional[str] = None):
    from ray_tpu.util.state import timeline as _timeline

    return _timeline(filename)


# Lazy submodules: heavy libraries (jax imports) load on first access.
_LAZY_SUBMODULES = ("util", "train", "data", "tune", "rllib", "serve", "workflow", "dag",
                    "models", "ops", "parallel", "autoscaler", "air", "experimental")


def __getattr__(name):
    if name in _LAZY_SUBMODULES:
        import importlib

        mod = importlib.import_module(f"ray_tpu.{name}")
        globals()[name] = mod
        return mod
    raise AttributeError(f"module 'ray_tpu' has no attribute '{name}'")
