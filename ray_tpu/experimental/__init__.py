from ray_tpu.experimental.channel import Channel, ChannelClosed

__all__ = ["Channel", "ChannelClosed"]
