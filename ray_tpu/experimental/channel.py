"""Channels for compiled DAGs: same-host shm ring buffers + cross-host sockets.

Reference: src/ray/core_worker/experimental_mutable_object_manager.h:48
and python/ray/experimental/channel/shared_memory_channel.py — a
fixed-size buffer written in place per message instead of allocating a
new object in the store per message.

``Channel`` is a single-writer / single-reader, same-host ring buffer
over an mmap'd file:

    [wbytes u64][rbytes u64][closed u64][pad..64][ring payload ...]

Records are ``[u64 len][payload][pad to 8]`` appended at ``wbytes %
capacity``; a len of 2**64-2 is a wrap marker (the rest of the region is
skipped), and the writer publishes ``wbytes`` only after the payload is
in place.  ``rbytes`` advancing IS the consume-ack: free space is
``capacity - (wbytes - rbytes)``, so the writer blocks only when the
ring is genuinely full — multiple messages ride in flight per edge
(pipelined compiled executions), unlike the previous one-slot seqlock
design which deadlocked any pipeline deeper than the edge count.
``closed`` is a drain-then-close flag: readers see ChannelClosed only
after consuming the backlog; blocked writers see it immediately.

``SocketChannel`` carries the same write/read/pending contract over one
long-lived TCP connection for compiled edges whose endpoints live on
different nodes: framed messages one way, consume-acks the other, a
bounded unacked window as flow control.  Either transport moves values
via the binary wire format (``_private/wire.py``) with ``write_value``
/ ``read_value`` — encoded straight into the ring / scratch frame, no
pickling and no intermediate copies for the fast-path types.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Any, List, Optional, Sequence, Tuple

_U64 = struct.Struct("<Q")
HEADER = 64
POISON = (1 << 64) - 1  # socket framing: orderly close
WRAP = (1 << 64) - 2  # ring: rest of region is skipped
_WOFF, _ROFF, _COFF = 0, 8, 16


def _align8(n: int) -> int:
    return (n + 7) & ~7


class ChannelClosed(Exception):
    """The peer closed the channel (drained) or died (socket EOF)."""


class ChannelTimeout(Exception):
    """The peer is alive but didn't produce/consume within the timeout."""


class ChannelCapacityError(ValueError):
    """Payload exceeds the channel's fixed capacity (typed, never a hang)."""


class ChannelConnectionError(ConnectionError):
    """A socket channel could not (re)connect: the listener accepts
    exactly one peer for its lifetime (single-writer/single-reader
    contract), so dialing a consumed or dead endpoint is refused."""


class Channel:
    kind = "ring"

    @staticmethod
    def create_file(path: str, max_size: int = 8 * 1024 * 1024) -> None:
        """Allocate a channel's backing file without opening an endpoint
        (the single place that knows the on-disk layout)."""
        with open(path, "wb") as f:
            f.truncate(HEADER + max_size)

    def __init__(self, path: str, max_size: int = 8 * 1024 * 1024, create: bool = False):
        self.path = path
        if create:
            with open(path, "wb") as f:
                f.truncate(HEADER + max_size)
        # Open by both sides; size from the file (reader may not know).
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        cap = size - HEADER
        self.capacity = cap - (cap % 8)
        # Largest single record (header + aligned payload) the ring can
        # carry: one wrap marker must always fit beside it.
        self.max_size = self.capacity - 16
        self._mm = mmap.mmap(self._f.fileno(), size)
        # Dataplane counters (item-2 hot path must land measurable):
        # plain dict increments on the fast path (~100 ns), folded into
        # telemetry in batches of _TELE_FLUSH_OPS so per-op cost stays
        # far inside the <5% budget at channel rates.
        self.stats = {
            "writes": 0,
            "reads": 0,
            "bytes_written": 0,
            "bytes_read": 0,
            "write_blocked_s": 0.0,
            "read_blocked_s": 0.0,
            "write_timeouts": 0,
            "read_timeouts": 0,
        }
        self._tele_ops = 0
        self._tele_flushed = dict(self.stats)

    # -- raw fields -----------------------------------------------------
    def _get(self, off: int) -> int:
        return _U64.unpack_from(self._mm, off)[0]

    def _set(self, off: int, v: int) -> None:
        _U64.pack_into(self._mm, off, v)

    # Hot-spinning only helps when the peer can run on another core;
    # on a 1-2 core host it starves the peer for a whole scheduler
    # quantum (~1 ms RTT).  sched_yield-first is ~10x faster there and
    # within noise on big hosts.
    _HOT_SPINS = 1500 if (os.cpu_count() or 1) > 2 else 0

    def _backoff(self, spins: int) -> None:
        """Latency-first wait: (multicore only) hot-spin ~0.1ms, then
        sched_yield, then ramp sleeps toward 1ms so a long-idle resident
        loop doesn't pin a core (the reference's channels busy-wait the
        same way)."""
        if spins < self._HOT_SPINS:
            return
        if spins < self._HOT_SPINS + 4000:
            time.sleep(0)
            return
        time.sleep(min(0.001, 0.00002 * (spins - self._HOT_SPINS - 3999)))

    _TELE_FLUSH_OPS = 512

    def _tele_flush(self) -> None:
        """Push counter deltas since the last flush to telemetry (one
        batched inc per series); called every _TELE_FLUSH_OPS ops, on
        timeout, and on close."""
        from ray_tpu._private import telemetry

        s, last = self.stats, self._tele_flushed
        telemetry.count_channel_ops("write", s["writes"] - last["writes"])
        telemetry.count_channel_ops("read", s["reads"] - last["reads"])
        telemetry.add_channel_blocked(
            "write", s["write_blocked_s"] - last["write_blocked_s"]
        )
        telemetry.add_channel_blocked(
            "read", s["read_blocked_s"] - last["read_blocked_s"]
        )
        telemetry.count_channel_timeout(
            "write", s["write_timeouts"] - last["write_timeouts"]
        )
        telemetry.count_channel_timeout(
            "read", s["read_timeouts"] - last["read_timeouts"]
        )
        self._tele_flushed = dict(s)
        self._tele_ops = 0

    def pending(self) -> bool:
        """Occupancy: published bytes the reader hasn't consumed yet."""
        try:
            return self._get(_WOFF) != self._get(_ROFF)
        except ValueError:
            return False  # mmap closed

    def _closed_flag(self) -> bool:
        try:
            return self._get(_COFF) != 0
        except ValueError:
            return True

    # -- writer ---------------------------------------------------------
    def _count_write(self, nbytes: int) -> None:
        s = self.stats
        s["writes"] += 1
        s["bytes_written"] += nbytes
        self._tele_ops += 1
        if self._tele_ops >= self._TELE_FLUSH_OPS:
            self._tele_flush()

    def _write_wait(self, spins: int, t_block: float, deadline: Optional[float]) -> float:
        """One blocked-writer backoff step (shared by write paths)."""
        if self._closed_flag():
            self.stats["write_blocked_s"] += time.monotonic() - t_block if spins else 0.0
            raise ChannelClosed(self.path)
        self._backoff(spins)
        if (
            deadline is not None
            and (spins >= 2000 or spins % 512 == 0)
            and time.monotonic() > deadline
        ):
            self.stats["write_timeouts"] += 1
            self.stats["write_blocked_s"] += time.monotonic() - t_block
            self._tele_flush()
            raise ChannelTimeout(
                f"reader of {self.path} did not free ring space in time"
            )
        return t_block

    def _wrap(self, wb: int, tail: int) -> int:
        """Write a wrap marker (when it fits) and skip the tail region.
        Caller has verified the tail is free."""
        wpos = wb % self.capacity
        if tail >= 8:
            _U64.pack_into(self._mm, HEADER + wpos, WRAP)
        wb += tail
        self._set(_WOFF, wb)
        return wb

    def write(self, data: bytes, timeout: Optional[float] = 30.0) -> None:
        need = 8 + _align8(len(data))
        if need > self.max_size:
            raise ChannelCapacityError(
                f"message of {len(data)} bytes exceeds channel capacity "
                f"{self.max_size}; raise the buffer size at compile time"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        cap = self.capacity
        while True:
            wb = self._get(_WOFF)
            free = cap - (wb - self._get(_ROFF))
            tail = cap - (wb % cap)
            if tail < need:
                # Wrap once the tail region is free, then retry.
                if free >= tail:
                    self._wrap(wb, tail)
                    continue
            elif free >= need:
                break
            if spins == 0:
                t_block = time.monotonic()
            spins += 1
            self._write_wait(spins, t_block, deadline)
        wpos = wb % cap
        self._mm[HEADER + wpos + 8 : HEADER + wpos + 8 + len(data)] = data
        _U64.pack_into(self._mm, HEADER + wpos, len(data))
        self._set(_WOFF, wb + need)
        if spins:
            self.stats["write_blocked_s"] += time.monotonic() - t_block
        self._count_write(len(data))

    def _try_publish_value(self, value: Any, tag: int) -> Tuple[bool, bool]:
        """One encode attempt at the current write position.  Returns
        (published, blocked_on_reader): encoding straight into the ring
        means the payload size is unknown up front, so an overflow is
        disambiguated by WHAT bounded the window — the region tail
        (fixable by wrapping), the reader's position (fixable by
        waiting), or the whole ring (typed capacity error)."""
        from ray_tpu._private import wire

        cap = self.capacity
        wb = self._get(_WOFF)
        free = cap - (wb - self._get(_ROFF))
        wpos = wb % cap
        tail = cap - wpos
        window = min(tail, free)
        if window >= 16:
            try:
                n = wire.encode_into(
                    memoryview(self._mm)[
                        HEADER + wpos + 8 : HEADER + wpos + window
                    ],
                    value,
                    tag,
                )
            except (struct.error, ValueError, IndexError):
                n = -1
            if n >= 0 and 8 + _align8(n) <= window:
                _U64.pack_into(self._mm, HEADER + wpos, n)
                self._set(_WOFF, wb + 8 + _align8(n))
                self._count_write(n)
                return True, False
        if window >= tail:
            # Tail-bounded: wrap (the tail is fully free) and retry.
            if tail >= cap - 16:
                # Full, empty ring couldn't hold it: genuinely too big.
                raise ChannelCapacityError(
                    f"value exceeds ring capacity {self.max_size} of "
                    f"{self.path}; raise the buffer size at compile time"
                )
            self._wrap(wb, tail)
            return False, False
        return False, True  # reader-bounded: wait for consumption

    def write_value(self, value: Any, tag: int = 0, timeout: Optional[float] = 30.0) -> None:
        """Fast-path write: wire-encode ``value`` directly into the ring.

        A reader-bounded attempt partially ENCODES into the free window
        before discovering it doesn't fit, so the blocked loop must not
        re-attempt until the reader has actually consumed something — a
        parked writer of a large payload would otherwise burn a core
        re-encoding the same prefix every backoff wakeup (the podracer
        profile found runners spending >90% of parked CPU there)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        blocked_at_rb = None  # _ROFF snapshot taken BEFORE the blocked attempt
        while True:
            rb_before = self._get(_ROFF)
            if blocked_at_rb is not None:
                if rb_before == blocked_at_rb:
                    spins += 1
                    self._write_wait(spins, t_block, deadline)
                    continue
                blocked_at_rb = None
            published, blocked = self._try_publish_value(value, tag)
            if published:
                if spins:
                    self.stats["write_blocked_s"] += time.monotonic() - t_block
                return
            if blocked:
                if spins == 0:
                    t_block = time.monotonic()
                # The pre-attempt snapshot is the race-safe anchor: a
                # reader advance DURING the attempt leaves _ROFF !=
                # rb_before, so the gate above retries immediately
                # instead of waiting on a ring the reader has already
                # drained (which would never advance again).
                blocked_at_rb = rb_before
                spins += 1
                self._write_wait(spins, t_block, deadline)

    def try_write_value(self, value: Any, tag: int = 0) -> bool:
        """Non-blocking write attempt (fan-out scheduling): False when
        the ring lacks free space right now."""
        if self._closed_flag():
            raise ChannelClosed(self.path)
        while True:
            published, blocked = self._try_publish_value(value, tag)
            if published:
                return True
            if blocked:
                return False
            # wrapped: retry immediately at the region start

    def close(self) -> None:
        """Drain-then-close: the reader sees ChannelClosed after
        consuming the backlog; blocked writers see it immediately.
        Either side may close (teardown path)."""
        try:
            self._tele_flush()
        except Exception:
            pass
        try:
            self._set(_COFF, 1)
        except ValueError:
            pass  # mmap already closed
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass

    # -- reader ---------------------------------------------------------
    def _read_slot(self) -> Optional[Tuple[int, int]]:
        """(rpos, len) of the next record, advancing past wrap markers;
        None when the ring is empty."""
        cap = self.capacity
        while True:
            rb = self._get(_ROFF)
            if self._get(_WOFF) == rb:
                return None
            rpos = rb % cap
            tail = cap - rpos
            if tail < 8:
                self._set(_ROFF, rb + tail)
                continue
            n = _U64.unpack_from(self._mm, HEADER + rpos)[0]
            if n == WRAP:
                self._set(_ROFF, rb + tail)
                continue
            return rpos, n

    def _consume(self, rpos: int, n: int, blocked_since: float) -> None:
        self._set(_ROFF, self._get(_ROFF) + 8 + _align8(n))
        s = self.stats
        s["reads"] += 1
        s["bytes_read"] += n
        if blocked_since:
            s["read_blocked_s"] += time.monotonic() - blocked_since
        self._tele_ops += 1
        if self._tele_ops >= self._TELE_FLUSH_OPS:
            self._tele_flush()

    def _read_wait(self, spins: int, t_block: float, deadline: Optional[float], timeout) -> None:
        if self._closed_flag():
            raise ChannelClosed(self.path)
        self._backoff(spins)
        if (
            deadline is not None
            and (spins >= 2000 or spins % 512 == 0)
            and time.monotonic() > deadline
        ):
            self.stats["read_timeouts"] += 1
            self.stats["read_blocked_s"] += time.monotonic() - t_block
            self._tele_flush()
            raise ChannelTimeout(f"no message on {self.path} within {timeout}s")

    def read(self, timeout: Optional[float] = 30.0) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        while True:
            slot = self._read_slot()
            if slot is not None:
                rpos, n = slot
                data = bytes(self._mm[HEADER + rpos + 8 : HEADER + rpos + 8 + n])
                self._consume(rpos, n, t_block if spins else 0.0)
                return data
            if spins == 0:
                t_block = time.monotonic()
            spins += 1
            self._read_wait(spins, t_block, deadline, timeout)

    def read_value(self, timeout: Optional[float] = 30.0) -> Tuple[int, Any]:
        """Fast-path read: wire-decode straight from the ring; returns
        ``(tag, value)``.  Array payloads are copied out before the
        consume-ack (the writer reuses the region afterwards)."""
        from ray_tpu._private import wire

        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        while True:
            slot = self._read_slot()
            if slot is not None:
                rpos, n = slot
                tag, value = wire.decode(
                    memoryview(self._mm)[HEADER + rpos + 8 : HEADER + rpos + 8 + n],
                    copy_arrays=True,
                )
                self._consume(rpos, n, t_block if spins else 0.0)
                return tag, value
            if spins == 0:
                t_block = time.monotonic()
            spins += 1
            self._read_wait(spins, t_block, deadline, timeout)

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Cross-host socket channels


_FRAME = struct.Struct("<Q")
_ACK = b"\x01"


class SocketListener:
    """One listening endpoint for one compiled edge.  Accepts exactly ONE
    connection over its lifetime (the single-writer/single-reader
    contract), then closes the listening socket — a later dial to the
    same port is refused (``ChannelConnectionError`` on the dialer)."""

    def __init__(self):
        import socket as _socket

        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(1)
        self.port = self._sock.getsockname()[1]

    def accept(self, role: str, timeout: Optional[float] = 30.0) -> "SocketChannel":
        import socket as _socket

        self._sock.settimeout(timeout)
        try:
            conn, _peer = self._sock.accept()
        except _socket.timeout:
            raise ChannelTimeout(
                f"no peer dialed listener :{self.port} within {timeout}s"
            ) from None
        finally:
            self.close()
        return SocketChannel(conn, role)

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def dial(addr: Tuple[str, int], role: str, timeout: float = 15.0) -> "SocketChannel":
    """Connect to a bound listener; retries transient refusals on the
    unified CONNECT policy until ``timeout`` (listener startup races),
    then raises the typed ``ChannelConnectionError``."""
    import socket as _socket

    from ray_tpu._private import retry, telemetry

    bo = retry.CONNECT.start(deadline_s=timeout)
    last: Optional[Exception] = None
    while True:
        try:
            sock = _socket.create_connection(tuple(addr), timeout=min(timeout, 5.0))
            telemetry.count_socket_connect("ok")
            return SocketChannel(sock, role)
        except OSError as e:
            last = e
            delay = bo.next_delay()
            if delay is None:
                telemetry.count_socket_connect("refused")
                raise ChannelConnectionError(
                    f"socket channel endpoint {addr} refused ({last}); "
                    "compiled-edge listeners accept exactly one connection — "
                    "a dropped edge means the graph must be recompiled"
                ) from last
            time.sleep(delay)


class SocketChannel:
    """The mmap ring's write/read/pending contract over one long-lived
    TCP connection (one per compiled REMOTE edge, chosen at compile time
    by placement).

    Data frames (``[u64 len][payload]``) flow writer→reader; one ack
    byte per *consumed* message flows back.  Flow control is a bounded
    unacked window (like the ring's single slot, widened to hide the
    network RTT).  Reader-side: a daemonized reader thread drains frames
    into a local queue so ``pending()`` is local and writer death (EOF /
    reset) is detected immediately as ``ChannelClosed`` — distinct from
    ``ChannelTimeout``, which means the peer is alive but silent.
    """

    kind = "socket"

    _CLOSED = object()  # poison frame received (orderly close)
    _DIED = object()  # EOF/reset without poison (peer death)

    def __init__(self, sock, role: str, window: Optional[int] = None):
        import queue as _queue
        import socket as _socket
        import threading as _threading

        assert role in ("read", "write"), role
        if window is None:
            from ray_tpu._private.config import CONFIG

            window = int(getattr(CONFIG, "socket_channel_window", 8))
        self.role = role
        self.path = f"socket:{sock.getpeername()}"
        self._sock = sock
        # A dialed socket inherits create_connection's CONNECT timeout;
        # left in place it would make every later sendall of a frame
        # larger than the kernel buffers raise socket.timeout (read as
        # ChannelClosed) when the peer is slow to drain.  Steady-state
        # blocking is governed by the ack-window flow control, not a
        # per-syscall timeout.
        self._sock.settimeout(None)
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._window = max(1, window)
        self._unacked = 0
        self._closed = False
        self.stats = {
            "writes": 0,
            "reads": 0,
            "bytes_written": 0,
            "bytes_read": 0,
            "write_blocked_s": 0.0,
            "read_blocked_s": 0.0,
            "write_timeouts": 0,
            "read_timeouts": 0,
        }
        self._tele_ops = 0
        self._tele_flushed = dict(self.stats)
        self._scratch = bytearray(64 * 1024)
        if role == "read":
            self._q: "_queue.Queue" = _queue.Queue()
            self._rx = _threading.Thread(
                target=self._rx_loop, daemon=True, name="socket-channel-rx"
            )
            self._rx.start()

    # Telemetry rides the SAME channel_* series as the ring (op labels
    # read/write) — one dataplane, two transports.
    _TELE_FLUSH_OPS = Channel._TELE_FLUSH_OPS
    _tele_flush = Channel._tele_flush

    # -- reader ---------------------------------------------------------
    def _recv_exact(self, n: int) -> Optional[bytes]:
        """None on EOF; runs only on the rx thread."""
        chunks = []
        while n:
            chunk = self._sock.recv(n)
            if not chunk:
                return None
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks) if len(chunks) != 1 else chunks[0]

    def _rx_loop(self) -> None:
        while True:
            try:
                head = self._recv_exact(8)
                if head is None:
                    self._q.put(self._DIED)
                    return
                (n,) = _FRAME.unpack(head)
                if n == POISON:
                    self._q.put(self._CLOSED)
                    return
                payload = self._recv_exact(n)
                if payload is None:
                    self._q.put(self._DIED)
                    return
                self._q.put(payload)
            except OSError:
                self._q.put(self._DIED)
                return

    def _pop_frame(self, timeout: Optional[float]) -> bytes:
        import queue as _queue

        t0 = time.monotonic()
        try:
            item = self._q.get(timeout=timeout)
        except _queue.Empty:
            self.stats["read_timeouts"] += 1
            self.stats["read_blocked_s"] += time.monotonic() - t0
            self._tele_flush()
            raise ChannelTimeout(
                f"no message on {self.path} within {timeout}s"
            ) from None
        waited = time.monotonic() - t0
        if waited > 0.0005:
            self.stats["read_blocked_s"] += waited
        if item is self._CLOSED or item is self._DIED:
            self._closed = True
            self._q.put(item)  # later reads fail the same way
            raise ChannelClosed(
                f"{self.path}: "
                + ("closed by writer" if item is self._CLOSED else "writer died")
            )
        # Consume-ack: flow control counts messages the CONSUMER has
        # taken, not what the rx thread buffered.
        try:
            self._sock.sendall(_ACK)
        except OSError:
            pass  # writer already gone; reads of buffered frames still valid
        s = self.stats
        s["reads"] += 1
        s["bytes_read"] += len(item)
        self._tele_ops += 1
        if self._tele_ops >= self._TELE_FLUSH_OPS:
            self._tele_flush()
        return item

    def read(self, timeout: Optional[float] = 30.0) -> bytes:
        return self._pop_frame(timeout)

    def read_value(self, timeout: Optional[float] = 30.0) -> Tuple[int, Any]:
        from ray_tpu._private import wire

        frame = self._pop_frame(timeout)
        # One-shot frame owned by us: arrays may alias it (no copy).
        return wire.decode(memoryview(frame), copy_arrays=False)

    def pending(self) -> bool:
        if self.role == "read":
            return not self._q.empty()
        return self._unacked > 0

    # -- writer ---------------------------------------------------------
    def _drain_acks(self, deadline: Optional[float]) -> None:
        """Consume available acks; when the window is full, block (up to
        the deadline) for the next one."""
        import select as _select

        while True:
            timeout = 0.0
            if self._unacked >= self._window:
                if deadline is None:
                    timeout = 1.0
                else:
                    timeout = max(0.0, deadline - time.monotonic())
                    if timeout == 0.0:
                        self.stats["write_timeouts"] += 1
                        self._tele_flush()
                        raise ChannelTimeout(
                            f"reader of {self.path} did not consume "
                            f"(window {self._window} full)"
                        )
            ready, _, _ = _select.select([self._sock], [], [], timeout)
            if not ready:
                if self._unacked < self._window:
                    return
                continue  # window full: keep waiting for the ack
            try:
                acks = self._sock.recv(4096)
            except OSError:
                acks = b""
            if not acks:
                self._closed = True
                raise ChannelClosed(f"{self.path}: reader died")
            self._unacked -= len(acks)
            if self._unacked < self._window:
                return

    def _send_frame(self, payload_len: int) -> None:
        _FRAME.pack_into(self._scratch, 0, payload_len)
        self._sock.sendall(memoryview(self._scratch)[: 8 + payload_len])

    def _encode_scratch(self, value: Any, tag: int) -> int:
        from ray_tpu._private import wire

        while True:
            try:
                return wire.encode_into(memoryview(self._scratch)[8:], value, tag)
            except (struct.error, ValueError, IndexError):
                if len(self._scratch) >= 1 << 31:
                    raise ChannelCapacityError(
                        "value exceeds socket channel frame limit (2 GiB)"
                    ) from None
                self._scratch = bytearray(len(self._scratch) * 4)

    def _write_payload(self, value: Any, tag: int, timeout: Optional[float], data: Optional[bytes]) -> None:
        if self._closed:
            raise ChannelClosed(self.path)
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = time.monotonic()
        try:
            self._drain_acks(deadline)
            if data is not None:
                n = len(data)
                if len(self._scratch) < 8 + n:
                    self._scratch = bytearray(8 + n)
                self._scratch[8 : 8 + n] = data
            else:
                n = self._encode_scratch(value, tag)
            self._send_frame(n)
        except OSError as e:
            self._closed = True
            raise ChannelClosed(f"{self.path}: {e}") from None
        waited = time.monotonic() - t0
        if waited > 0.0005:
            self.stats["write_blocked_s"] += waited
        self._unacked += 1
        self._count_write(n)

    _count_write = Channel._count_write

    def write(self, data: bytes, timeout: Optional[float] = 30.0) -> None:
        self._write_payload(None, 0, timeout, data)

    def write_value(self, value: Any, tag: int = 0, timeout: Optional[float] = 30.0) -> None:
        self._write_payload(value, tag, timeout, None)

    def try_write_value(self, value: Any, tag: int = 0) -> bool:
        if self._closed:
            raise ChannelClosed(self.path)
        if self._unacked >= self._window:
            import select as _select

            ready, _, _ = _select.select([self._sock], [], [], 0.0)
            if ready:
                try:
                    acks = self._sock.recv(4096)
                except OSError:
                    acks = b""
                if not acks:
                    self._closed = True
                    raise ChannelClosed(f"{self.path}: reader died")
                self._unacked -= len(acks)
            if self._unacked >= self._window:
                return False
        self.write_value(value, tag, timeout=None)
        return True

    # -- teardown -------------------------------------------------------
    def close(self) -> None:
        try:
            self._tele_flush()
        except Exception:
            pass
        if self.role == "write" and not self._closed:
            try:
                self._sock.sendall(_FRAME.pack(POISON))
            except OSError:
                pass
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass

    def unlink(self) -> None:  # contract parity with the ring
        pass


# ---------------------------------------------------------------------------
# Shared-memory fan-out: one writer, N same-node readers
#
# Broadcasting one payload to N co-located consumers (pipeline weight
# restore, activation/weight broadcast) previously cost N duplicate ring
# writes — N encodes and N payload copies through N rings.  A fan-out
# ring stores the payload ONCE; each reader owns a consume cursor, and
# the writer's free space is bounded by the SLOWEST reader (min over
# cursors), so flow control degrades exactly like a single-reader ring.
#
#     [wbytes u64][closed u64][n_readers u64][r0 u64]..[rN-1 u64][pad]
#     [ring payload: [u64 len][data][pad8] / WRAP markers ...]


def _fanout_header(n_readers: int) -> int:
    return ((24 + 8 * n_readers + 63) // 64) * 64


class FanoutChannel:
    """Writer endpoint of a 1-to-N shm ring: write once, every reader
    consumes independently (N consume-acks)."""

    kind = "fanout"

    def __init__(self, path: str, n_readers: int,
                 max_size: int = 8 * 1024 * 1024, create: bool = False):
        if n_readers < 1:
            raise ValueError("fan-out channel needs at least one reader")
        self.path = path
        self.n_readers = n_readers
        header = _fanout_header(n_readers)
        if create:
            with open(path, "wb") as f:
                f.truncate(header + max_size)
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        self._header = header
        cap = size - header
        self.capacity = cap - (cap % 8)
        self.max_size = self.capacity - 16
        self._mm = mmap.mmap(self._f.fileno(), size)
        if create:
            _U64.pack_into(self._mm, 16, n_readers)
        else:
            stored = _U64.unpack_from(self._mm, 16)[0]
            if stored != n_readers:
                raise ValueError(
                    f"fan-out channel {path} was created for {stored} "
                    f"readers, opened for {n_readers}"
                )
        self.stats = {"writes": 0, "bytes_written": 0, "write_blocked_s": 0.0}

    def _reader_off(self, idx: int) -> int:
        return 24 + 8 * idx

    def _min_read(self) -> int:
        return min(
            _U64.unpack_from(self._mm, self._reader_off(i))[0]
            for i in range(self.n_readers)
        )

    def write(self, data: bytes, timeout: Optional[float] = 30.0) -> None:
        need = 8 + _align8(len(data))
        if need > self.max_size:
            raise ChannelCapacityError(
                f"message of {len(data)} bytes exceeds fan-out channel "
                f"capacity {self.max_size}; raise the buffer size"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        cap = self.capacity
        hdr = self._header
        while True:
            if _U64.unpack_from(self._mm, 8)[0]:
                raise ChannelClosed(self.path)
            wb = _U64.unpack_from(self._mm, 0)[0]
            free = cap - (wb - self._min_read())
            tail = cap - (wb % cap)
            if tail < need:
                if free >= tail:
                    # Wrap: the tail region is free for EVERY reader.
                    if tail >= 8:
                        _U64.pack_into(self._mm, hdr + (wb % cap), WRAP)
                    _U64.pack_into(self._mm, 0, wb + tail)
                    continue
            elif free >= need:
                break
            if spins == 0:
                t_block = time.monotonic()
            spins += 1
            if spins < 4000:
                time.sleep(0)
            else:
                time.sleep(min(0.001, 0.00002 * (spins - 3999)))
            if deadline is not None and time.monotonic() > deadline:
                self.stats["write_blocked_s"] += time.monotonic() - t_block
                raise ChannelTimeout(
                    f"slowest of {self.n_readers} fan-out readers of "
                    f"{self.path} did not free ring space in time"
                )
        wpos = wb % cap
        self._mm[hdr + wpos + 8: hdr + wpos + 8 + len(data)] = data
        _U64.pack_into(self._mm, hdr + wpos, len(data))
        _U64.pack_into(self._mm, 0, wb + need)
        if spins:
            self.stats["write_blocked_s"] += time.monotonic() - t_block
        self.stats["writes"] += 1
        self.stats["bytes_written"] += len(data)

    def write_value(self, value: Any, tag: int = 0,
                    timeout: Optional[float] = 30.0) -> None:
        """One encode, N consumers.  The broadcast path is not the
        per-microbatch hot loop, so the simple encode-then-copy beats
        duplicating the ring's in-place encoder for a third layout."""
        from ray_tpu._private import wire

        self.write(wire.encode(value, tag), timeout=timeout)

    def close(self) -> None:
        try:
            _U64.pack_into(self._mm, 8, 1)
        except ValueError:
            pass
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class FanoutReader:
    """Reader endpoint ``index`` of a :class:`FanoutChannel`: consumes
    every message exactly once at its own pace; advancing its cursor IS
    its consume-ack."""

    kind = "fanout"

    def __init__(self, path: str, index: int):
        self.path = path
        self.index = index
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        self._mm = mmap.mmap(self._f.fileno(), size)
        n = _U64.unpack_from(self._mm, 16)[0]
        if not 0 <= index < n:
            raise ValueError(f"reader index {index} out of range (n={n})")
        self.n_readers = n
        self._header = _fanout_header(n)
        cap = size - self._header
        self.capacity = cap - (cap % 8)
        self._off = 24 + 8 * index
        self.stats = {"reads": 0, "bytes_read": 0, "read_blocked_s": 0.0}

    def pending(self) -> bool:
        try:
            return (
                _U64.unpack_from(self._mm, 0)[0]
                != _U64.unpack_from(self._mm, self._off)[0]
            )
        except ValueError:
            return False

    def _next_slot(self) -> Optional[Tuple[int, int]]:
        cap = self.capacity
        while True:
            rb = _U64.unpack_from(self._mm, self._off)[0]
            if _U64.unpack_from(self._mm, 0)[0] == rb:
                return None
            rpos = rb % cap
            tail = cap - rpos
            if tail < 8:
                _U64.pack_into(self._mm, self._off, rb + tail)
                continue
            n = _U64.unpack_from(self._mm, self._header + rpos)[0]
            if n == WRAP:
                _U64.pack_into(self._mm, self._off, rb + tail)
                continue
            return rpos, n

    def read(self, timeout: Optional[float] = 30.0) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        while True:
            slot = self._next_slot()
            if slot is not None:
                rpos, n = slot
                data = bytes(
                    self._mm[self._header + rpos + 8: self._header + rpos + 8 + n]
                )
                rb = _U64.unpack_from(self._mm, self._off)[0]
                _U64.pack_into(self._mm, self._off, rb + 8 + _align8(n))
                self.stats["reads"] += 1
                self.stats["bytes_read"] += n
                if spins:
                    self.stats["read_blocked_s"] += time.monotonic() - t_block
                return data
            if _U64.unpack_from(self._mm, 8)[0]:
                raise ChannelClosed(self.path)
            if spins == 0:
                t_block = time.monotonic()
            spins += 1
            if spins < 4000:
                time.sleep(0)
            else:
                time.sleep(min(0.001, 0.00002 * (spins - 3999)))
            if deadline is not None and time.monotonic() > deadline:
                self.stats["read_blocked_s"] += time.monotonic() - t_block
                raise ChannelTimeout(
                    f"no fan-out message on {self.path} within {timeout}s"
                )

    def read_value(self, timeout: Optional[float] = 30.0) -> Tuple[int, Any]:
        from ray_tpu._private import wire

        # The frame was copied out of the ring by read(); arrays may
        # alias the private copy.
        return wire.decode(memoryview(self.read(timeout)), copy_arrays=False)

    def close(self) -> None:
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Compile-time endpoint plumbing


# Listeners bound during a compiled graph's setup phase, consumed when
# its resident loop (or the driver) opens the read side.  Keyed by
# (dag token, channel id); same process between setup and loop start.
_BOUND_LISTENERS: dict = {}


def bind_listener(token: str, cid: str) -> int:
    lst = SocketListener()
    _BOUND_LISTENERS[(token, cid)] = lst
    return lst.port


def take_listener(token: str, cid: str) -> SocketListener:
    return _BOUND_LISTENERS.pop((token, cid))


def drop_listeners(token: str) -> None:
    for key in [k for k in _BOUND_LISTENERS if k[0] == token]:
        _BOUND_LISTENERS.pop(key).close()


def ring_base_dir() -> str:
    """Filesystem base for ring-channel files: tmpfs when available.
    The single place that picks it — compiled-DAG and serve ring
    directories must land on the same filesystem."""
    import tempfile

    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def node_hosts(worker) -> dict:
    """node id (hex) -> reachable host, from the GCS cluster view.
    Local (unix-socket) raylets are same-machine by definition."""
    from ray_tpu._private.ids import NodeID

    info = worker.gcs_client.call("get_cluster_info")
    hosts = {}
    for n in info["nodes"].values():
        addr = str(n.get("raylet_address", ""))
        if addr.startswith("unix:") or ":" not in addr:
            host = "127.0.0.1"
        else:
            host = addr.rsplit(":", 1)[0] or "127.0.0.1"
        if host == "0.0.0.0":
            host = "127.0.0.1"
        hosts[NodeID(n["node_id"]).hex()] = host
    return hosts


def open_channel(desc: dict, role: str, timeout: float = 30.0):
    """Open one endpoint of a planned channel.

    ``desc`` is the compile-time descriptor: ``{"kind": "ring", "path"}``
    or ``{"kind": "socket", "token", "id", "addr": (host, port)}``.
    Socket rule: the READER bound the listener during setup (and accepts
    here); the WRITER dials.  Dials never deadlock accepts because every
    listener is bound before any loop starts (TCP completes the
    handshake from the backlog).
    """
    if desc["kind"] == "ring":
        return Channel(desc["path"])
    if role == "write":
        return dial(tuple(desc["addr"]), "write", timeout=timeout)
    return take_listener(desc["token"], desc["id"]).accept("read", timeout=timeout)


def write_value_fanout(
    targets: Sequence[Tuple[Any, Any, int]], timeout: Optional[float] = None
) -> None:
    """Write a batch of (channel, value, tag) with fan-out overlap: each
    blocked edge is retried round-robin via ``try_write_value`` so one
    slow consumer never head-of-line-blocks an independent branch (the
    graph-level scheduling rule: issue every fan-out write before
    blocking on any single peer)."""
    if len(targets) == 1:
        chan, value, tag = targets[0]
        chan.write_value(value, tag, timeout=timeout)
        return
    pending: List[Tuple[Any, Any, int]] = list(targets)
    deadline = None if timeout is None else time.monotonic() + timeout
    spins = 0
    while pending:
        rest = []
        for chan, value, tag in pending:
            if not chan.try_write_value(value, tag):
                rest.append((chan, value, tag))
        if not rest:
            return
        pending = rest
        spins += 1
        if spins > 1000:
            time.sleep(min(0.001, 0.00002 * (spins - 1000)))
        else:
            time.sleep(0)
        if deadline is not None and time.monotonic() > deadline:
            raise ChannelTimeout(
                f"{len(pending)} fan-out peers did not consume within {timeout}s"
            )
