"""Channels for compiled DAGs: same-host shm ring buffers + cross-host sockets.

Reference: src/ray/core_worker/experimental_mutable_object_manager.h:48
and python/ray/experimental/channel/shared_memory_channel.py — a
fixed-size buffer written in place per message instead of allocating a
new object in the store per message.

``Channel`` is a single-writer / single-reader, same-host ring buffer
over an mmap'd file:

    [wbytes u64][rbytes u64][closed u64][pad..64][ring payload ...]

Records are ``[u64 len][payload][u32 crc32][pad to 8]`` appended at
``wbytes % capacity``; a len of 2**64-2 is a wrap marker (the rest of
the region is skipped), and the writer publishes ``wbytes`` only after
the payload AND its CRC trailer are in place.  ``rbytes`` advancing IS
the consume-ack: free space is ``capacity - (wbytes - rbytes)``, so the
writer blocks only when the ring is genuinely full — multiple messages
ride in flight per edge (pipelined compiled executions).  ``closed`` is
a drain-then-close flag: readers see ChannelClosed only after consuming
the backlog; blocked writers see it immediately.

Frame integrity: every record carries a CRC32 trailer validated on
read.  A mismatch (bit rot, a torn write from a SIGKILLed writer, a
chaos ``corrupt_frame``/``torn_write`` injection) consumes the garbage
record and raises the typed ``ChannelCorruptionError`` — a corrupted
frame is NEVER delivered as data.  An implausible record length (torn
header) raises the same error without advancing (the ring framing is
unrecoverable from that position; the consumer's heavy recovery path
owns it).

``SocketChannel`` carries the same write/read/pending contract over one
long-lived TCP connection for compiled edges whose endpoints live on
different nodes: framed messages one way (``[u64 len][u64 seq][payload]
[u32 crc]``), consume-acks the other, a bounded unacked window as flow
control.  Channels carry an **epoch**: after a connection-level death
the writer may re-dial its reader's still-open listener with the
listener's pairing token at a bumped epoch, and frames the reader never
received are replayed from the writer's bounded unacked-frame buffer
(seq-resume; duplicates are dropped by seq).  ``reattach(chan)`` is the
one shared recovery helper the DAG / serve / stream attach paths call
on ``ChannelClosed`` before falling back to their heavy per-consumer
recovery.

Chaos: when the fault plane (``_private/chaos.py``) is active, every
write consults ``chan:<path-glob>:<action>`` rules — ``drop_frame``,
``delay_frame``, ``corrupt_frame``, ``torn_write``, ``close`` — so the
layer that carries all dataplane traffic is drillable with the same
seeded, replayable schedule as the RPC plane.

Orphan reclamation: every endpoint opened under a managed ring
directory registers its PID in ``<dir>/.pids``;
``sweep_orphan_ring_dirs()`` (run by the raylet) reclaims directories
whose registered owners are ALL dead — the tmpfs leak after SIGKILL.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
import zlib
from typing import Any, List, Optional, Sequence, Tuple

_U64 = struct.Struct("<Q")
_U32C = struct.Struct("<I")
HEADER = 64
POISON = (1 << 64) - 1  # socket framing: orderly close
WRAP = (1 << 64) - 2  # ring: rest of region is skipped
_WOFF, _ROFF, _COFF = 0, 8, 16


def _align8(n: int) -> int:
    return (n + 7) & ~7


def _u64_view(mm, nbytes: int):
    """Aligned u64 accessor over the mmap's first ``nbytes`` bytes.

    Shared header fields (write/read offsets, cursors, closed flag) are
    mutated by one process while the peer polls them.  struct's "<Q"
    pack/unpack loops BYTE-WISE, so a peer that preempts the writer
    mid-store observes a torn offset — seen in practice as phantom
    zero-length records on contended single-core hosts (the reader
    passes the occupancy check on the torn value, then reads a length
    word the writer hasn't stored yet).  A cast-memoryview item access
    is one aligned 8-byte load/store, which x86-64 keeps atomic."""
    return memoryview(mm)[:nbytes].cast("Q")


class ChannelClosed(Exception):
    """The peer closed the channel (drained) or died (socket EOF)."""


class ChannelTimeout(Exception):
    """The peer is alive but didn't produce/consume within the timeout."""


class ChannelCapacityError(ValueError):
    """Payload exceeds the channel's fixed capacity (typed, never a hang)."""


class ChannelConnectionError(ConnectionError):
    """A socket channel could not (re)connect: the endpoint is dead,
    or a reconnect handshake was refused (bad pairing token / stale
    epoch)."""


class ChannelCorruptionError(Exception):
    """A frame failed integrity validation (CRC32 trailer mismatch,
    torn record, undecodable payload).  The garbage is consumed where
    the framing allows it and NEVER delivered as data.

    ``advanced`` tells the consumer whether the read cursor moved past
    the garbage: True (the default) means the next read returns the
    next frame, so skip-and-continue is safe; False (torn/implausible
    record LENGTH — the framing itself is broken) means a retry re-reads
    the same garbage forever, so the consumer must run its heavy
    recovery instead of retrying."""

    advanced = True


class _DefaultTimeout:
    def __repr__(self):  # shows up in signatures/help
        return "<channel_default_timeout_s>"


#: Sentinel default for every channel read/write timeout: resolved at
#: call time from CONFIG.channel_default_timeout_s (one knob, so drills
#: can tighten every edge uniformly).  Pass None to block forever.
DEFAULT_TIMEOUT = _DefaultTimeout()


# ((env string, override value), resolved float) — CONFIG.get does a
# live os.environ read per access (~1.6 us), far too hot for a per-frame
# path; keying the cache on the raw env value AND the system_config
# override keeps the knob live through both routes (tests flip the env
# between ops; init(system_config=...) may land after early channel
# ops) at dict-lookup cost.  Only consulted when an op actually blocks.
_timeout_cache: Tuple[Any, Optional[float]] = (None, None)

_wire = None  # lazy module ref: the per-frame paths skip the import dance


def _wire_mod():
    global _wire
    if _wire is None:
        from ray_tpu._private import wire

        _wire = wire
    return _wire


_tracing = None  # lazy module ref (same pattern as _wire)


def _tracing_mod():
    global _tracing
    if _tracing is None:
        from ray_tpu.util import tracing

        _tracing = tracing
    return _tracing


def _trace_begin():
    """Per-frame write-side trace state: ``None`` when the writing
    context is untraced (ONE contextvar read — the untraced hot path
    pays nothing else), otherwise a mutable ``[trace_id, write_span_id,
    caller_span_id, t_entry]``.  One state per (frame, target) so every
    channel edge gets its own write span and blocked retries of the
    same frame never mint new span ids."""
    tr = _tracing_mod()
    ctx = tr.current_context()
    if ctx is None:
        return None
    return [ctx[0], tr.new_span_id(), ctx[1], time.time()]


def _trace_trailer(ts):
    """Wire trailer for one publish attempt.  write_ts is re-stamped per
    attempt so the committed frame carries ~commit time, making the
    reader's queue-wait attribution blocked-writer-proof."""
    return (ts[0], ts[1], 0, time.time())


def _trace_commit_write(ts, kind: str, path: str) -> None:
    """Record the frame's ``channel.write`` span (entry → commit) at the
    pre-minted write span id, parented under the caller's span."""
    _tracing_mod().record_span(
        "channel.write",
        ts[3],
        time.time(),
        {"kind": kind, "path": path},
        context=(ts[0], ts[1], ts[2]),
    )


def _trace_read(tr_tuple, kind: str, path: str):
    """Record the read-side hop span for a traced frame and return the
    frame context ``(trace_id, read_span_id, write_span_id)`` consumers
    adopt via ``tracing.set_frame_context``.  The span covers
    write-commit → read-return, so its duration IS the edge's queue
    wait (same-host clocks for rings; sockets carry the writer's stamp,
    close enough for attribution)."""
    trm = _tracing_mod()
    tid, wsid, _flags, wts = tr_tuple
    rsid = trm.new_span_id()
    end = time.time()
    start = wts if 0 < wts <= end else end
    trm.record_span(
        "channel.read",
        start,
        end,
        {"kind": kind, "path": path, "queue_wait_s": max(0.0, end - start)},
        context=(tid, rsid, wsid),
    )
    return (tid, rsid, wsid)


def _trace_reattach(path: str, ok: bool, epoch: int) -> None:
    """A reattach is an ANNOTATED event on the live trace (child span
    when a context is active, standalone event span otherwise) — never a
    break in the tree."""
    try:
        trm = _tracing_mod()
        now = time.time()
        attrs = {"path": path, "result": "ok" if ok else "failed",
                 "epoch": epoch}
        ctx = trm.current_context()
        if ctx is not None:
            trm.record_span(
                "channel.reattach", now, now, attrs,
                context=(ctx[0], trm.new_span_id(), ctx[1]),
            )
        else:
            trm.record_event_span("channel.reattach", now, now, attrs)
    except Exception:
        pass


def _resolve_timeout(timeout) -> Optional[float]:
    if timeout is not DEFAULT_TIMEOUT:
        return timeout
    global _timeout_cache
    from ray_tpu._private.config import CONFIG

    key = (
        os.environ.get("RAY_TPU_channel_default_timeout_s"),
        CONFIG._overrides.get("channel_default_timeout_s"),
    )
    cached_key, val = _timeout_cache
    if key == cached_key and val is not None:
        return val
    val = float(CONFIG.channel_default_timeout_s)
    _timeout_cache = (key, val)
    return val


# (plane, plane.rev at last check, active at last check): the no-chaos
# fast path is one int compare per frame instead of the plane's
# monotonic-throttled revalidation.  CHAOS.reset() bumps rev, so tests
# that flip the spec in-process are picked up on the very next frame;
# worker processes get their spec from the env at spawn (first check).
_chaos_cache = (None, -1, False)

#: "not decided yet" sentinel for try_write_value's ``cd`` parameter —
#: distinct from None, which means "decided: clean".
_CHAOS_UNDECIDED = object()


def _mutate_payload(mm, base: int, n: int, crc: int, cd) -> int:
    """Post-CRC payload mutation for corrupt_frame / torn_write, shared
    by the ring and fan-out writers (ONE fault model, not per-transport
    copies).  Both actions guarantee a CRC mismatch on read: corrupt
    flips a payload byte after the trailer was computed; torn models a
    writer killed mid-record (latter half never written, trailer
    stale).  The socket writer models torn differently by design — a
    mid-frame connection cut (see SocketChannel._write_payload).
    ``base`` is the absolute offset of the payload's first byte."""
    if cd.corrupt:
        if n > 0:
            mm[base] ^= 0xFF
        else:
            crc ^= 0xFFFFFFFF
    if cd.torn:
        half = n // 2
        if n - half > 0:
            mm[base + half : base + n] = b"\x00" * (n - half)
        crc ^= 0xA5A5A5A5
    return crc & 0xFFFFFFFF


def _chaos_decide(path: str):
    """Per-frame fault verdict (None on the no-chaos fast path)."""
    global _chaos_cache
    c, rev, active = _chaos_cache
    if c is None:
        from ray_tpu._private.chaos import CHAOS as c0

        c = c0
        rev = -1
    if rev != c.rev:
        # full (throttled) spec revalidation; an RPC-only spec leaves
        # the dataplane fast path untouched
        active = c.active and c.has_channel_rules
        _chaos_cache = (c, c.rev, active)
    if not active:
        return None
    d = c.decide_channel(path)
    return None if d.clean else d


def _chaos_net_decide(peer_addr):
    """Directional link verdict for one socket-channel dial or frame
    toward ``peer_addr`` (None on the no-net-chaos fast path).  The dst
    identity is ``addr:<host>:<port>`` — an RPC-plane partition
    (``net:raylet*->gcs:cut``) leaves the compiled dataplane connected
    unless a rule targets the channel address explicitly
    (``net:node1->addr:*:cut``)."""
    if peer_addr is None:
        return None
    from ray_tpu._private.chaos import CHAOS, net_name

    if not (CHAOS.active and CHAOS.has_net_rules):
        return None
    d = CHAOS.decide_net(net_name(), f"addr:{peer_addr[0]}:{peer_addr[1]}")
    return None if d.clean else d


def _count_corruption() -> None:
    try:
        from ray_tpu._private import telemetry

        telemetry.count_channel_corruption()
    except Exception:
        pass


def _count_reattach(ok: bool) -> None:
    try:
        from ray_tpu._private import telemetry

        telemetry.count_channel_reattach("ok" if ok else "failed")
    except Exception:
        pass


def _register_shm_pid(path: str) -> None:
    """Record this process as an owner of the ring directory holding
    ``path`` (sweep registry; see sweep_orphan_ring_dirs).  Only
    sweep-managed dirs (ray_tpu_* directly under ring_base_dir) are
    registered — test channels in tmp dirs are untouched."""
    d = os.path.dirname(path)
    if not os.path.basename(d).startswith("ray_tpu_"):
        return
    if os.path.dirname(d) != ring_base_dir():
        return
    try:
        with open(os.path.join(d, ".pids"), "a") as f:
            f.write(f"{os.getpid()}\n")
    except OSError:
        pass


_SMALL_HOST = (os.cpu_count() or 1) <= 2


def _poll_wait(spins: int) -> None:
    """One blocked-poll backoff step (spins counts from 0 per block).

    Big hosts: sched_yield for ~4k spins, then ramp sleeps 20us -> 1ms
    so a long-idle resident loop doesn't pin a core (the reference's
    channels busy-wait the same way).

    Small (1-2 core) hosts: the peer needs THIS core, and sched_yield
    may return without descheduling the caller (EEVDF keeps an eligible
    task running), so a yield phase can starve the peer for the whole
    quantum.  Go straight to tiny timer sleeps — a sleep always cedes
    the core, waking in ~0.1ms — then ramp to 1ms the same way.
    """
    if _SMALL_HOST:
        if spins < 256:
            time.sleep(0.000001)
        else:
            time.sleep(min(0.001, 0.00002 * (spins - 255)))
    elif spins < 4000:
        time.sleep(0)
    else:
        time.sleep(min(0.001, 0.00002 * (spins - 3999)))


def _pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except OSError:
        return True  # EPERM etc: it exists
    return True


class Channel:
    kind = "ring"

    @staticmethod
    def create_file(path: str, max_size: int = 8 * 1024 * 1024) -> None:
        """Allocate a channel's backing file without opening an endpoint
        (the single place that knows the on-disk layout)."""
        with open(path, "wb") as f:
            f.truncate(HEADER + max_size)

    def __init__(self, path: str, max_size: int = 8 * 1024 * 1024, create: bool = False):
        self.path = path
        if create:
            with open(path, "wb") as f:
                f.truncate(HEADER + max_size)
        # Open by both sides; size from the file (reader may not know).
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        cap = size - HEADER
        self.capacity = cap - (cap % 8)
        # Largest single payload (header + aligned payload + CRC) the
        # ring can carry: one wrap marker must always fit beside it.
        self.max_size = self.capacity - 24
        self._mm = mmap.mmap(self._f.fileno(), size)
        self._hdr = _u64_view(self._mm, HEADER)
        _register_shm_pid(path)
        # Dataplane counters (item-2 hot path must land measurable):
        # plain dict increments on the fast path (~100 ns), folded into
        # telemetry in batches of _TELE_FLUSH_OPS so per-op cost stays
        # far inside the <5% budget at channel rates.
        self.stats = {
            "writes": 0,
            "reads": 0,
            "bytes_written": 0,
            "bytes_read": 0,
            "write_blocked_s": 0.0,
            "read_blocked_s": 0.0,
            "write_timeouts": 0,
            "read_timeouts": 0,
            "corruptions": 0,
        }
        self._tele_ops = 0
        self._tele_flushed = dict(self.stats)

    # -- raw fields (single atomic 8-byte access; see _u64_view) --------
    def _get(self, off: int) -> int:
        return self._hdr[off >> 3]

    def _set(self, off: int, v: int) -> None:
        self._hdr[off >> 3] = v

    # Hot-spinning only helps when the peer can run on another core;
    # on a 1-2 core host it starves the peer for a whole scheduler
    # quantum (~1 ms RTT).  sched_yield-first is ~10x faster there and
    # within noise on big hosts.
    _HOT_SPINS = 1500 if (os.cpu_count() or 1) > 2 else 0

    def _backoff(self, spins: int) -> None:
        """Latency-first wait: (multicore only) hot-spin ~0.1ms, then
        the host-size-aware poll wait (see _poll_wait)."""
        if spins < self._HOT_SPINS:
            return
        _poll_wait(spins - self._HOT_SPINS)

    _TELE_FLUSH_OPS = 512

    def _tele_flush(self) -> None:
        """Push counter deltas since the last flush to telemetry (one
        batched inc per series); called every _TELE_FLUSH_OPS ops, on
        timeout, and on close."""
        from ray_tpu._private import telemetry

        s, last = self.stats, self._tele_flushed
        telemetry.count_channel_ops("write", s["writes"] - last["writes"])
        telemetry.count_channel_ops("read", s["reads"] - last["reads"])
        telemetry.add_channel_blocked(
            "write", s["write_blocked_s"] - last["write_blocked_s"]
        )
        telemetry.add_channel_blocked(
            "read", s["read_blocked_s"] - last["read_blocked_s"]
        )
        telemetry.count_channel_timeout(
            "write", s["write_timeouts"] - last["write_timeouts"]
        )
        telemetry.count_channel_timeout(
            "read", s["read_timeouts"] - last["read_timeouts"]
        )
        self._tele_flushed = dict(s)
        self._tele_ops = 0

    def pending(self) -> bool:
        """Occupancy: published bytes the reader hasn't consumed yet."""
        try:
            return self._get(_WOFF) != self._get(_ROFF)
        except ValueError:
            return False  # mmap closed

    def _closed_flag(self) -> bool:
        try:
            return self._get(_COFF) != 0
        except ValueError:
            return True

    # -- writer ---------------------------------------------------------
    def _count_write(self, nbytes: int) -> None:
        s = self.stats
        s["writes"] += 1
        s["bytes_written"] += nbytes
        self._tele_ops += 1
        if self._tele_ops >= self._TELE_FLUSH_OPS:
            self._tele_flush()

    def _record_corruption(self) -> None:
        self.stats["corruptions"] += 1
        _count_corruption()

    def _write_wait(self, spins: int, t_block: float, deadline: Optional[float]) -> float:
        """One blocked-writer backoff step (shared by write paths)."""
        if self._closed_flag():
            self.stats["write_blocked_s"] += time.monotonic() - t_block if spins else 0.0
            raise ChannelClosed(self.path)
        self._backoff(spins)
        if (
            deadline is not None
            and (spins >= 2000 or spins % 512 == 0)
            and time.monotonic() > deadline
        ):
            self.stats["write_timeouts"] += 1
            self.stats["write_blocked_s"] += time.monotonic() - t_block
            self._tele_flush()
            raise ChannelTimeout(
                f"reader of {self.path} did not free ring space in time"
            )
        return t_block

    def _wrap(self, wb: int, tail: int) -> int:
        """Write a wrap marker (when it fits) and skip the tail region.
        Caller has verified the tail is free."""
        wpos = wb % self.capacity
        if tail >= 8:
            _U64.pack_into(self._mm, HEADER + wpos, WRAP)
        wb += tail
        self._set(_WOFF, wb)
        return wb

    def _apply_write_chaos(self, cd, nbytes: int):
        """Pre-publish actions of one frame's fault verdict.  Returns
        True when the frame must be silently dropped; raises for close.
        corrupt/torn mutate at publish time (the caller passes cd down)."""
        if cd.delay_s > 0:
            time.sleep(cd.delay_s)
        if cd.close:
            self.close()
            raise ChannelClosed(f"{self.path}: chaos close")
        if cd.drop:
            self._count_write(nbytes)
            return True
        return False

    def _chaos_mutate(self, cd, wpos: int, n: int, crc: int) -> int:
        return _mutate_payload(self._mm, HEADER + wpos + 8, n, crc, cd)

    def write(self, data: bytes, timeout=DEFAULT_TIMEOUT) -> None:
        cd = _chaos_decide(self.path)
        if cd is not None and self._apply_write_chaos(cd, len(data)):
            return
        need = 8 + _align8(len(data) + 4)
        if need > self.max_size:
            raise ChannelCapacityError(
                f"message of {len(data)} bytes exceeds channel capacity "
                f"{self.max_size}; raise the buffer size at compile time"
            )
        deadline = None  # resolved at first block: the happy path never
        spins = 0        # pays the timeout-knob lookup
        t_block = 0.0
        cap = self.capacity
        while True:
            wb = self._get(_WOFF)
            free = cap - (wb - self._get(_ROFF))
            tail = cap - (wb % cap)
            if tail < need:
                # Wrap once the tail region is free, then retry.
                if free >= tail:
                    self._wrap(wb, tail)
                    continue
            elif free >= need:
                break
            if spins == 0:
                t_block = time.monotonic()
                timeout = _resolve_timeout(timeout)
                deadline = None if timeout is None else t_block + timeout
            spins += 1
            self._write_wait(spins, t_block, deadline)
        wpos = wb % cap
        self._mm[HEADER + wpos + 8 : HEADER + wpos + 8 + len(data)] = data
        crc = zlib.crc32(data)
        if cd is not None:
            crc = self._chaos_mutate(cd, wpos, len(data), crc)
        _U32C.pack_into(self._mm, HEADER + wpos + 8 + len(data), crc)
        _U64.pack_into(self._mm, HEADER + wpos, len(data))
        self._set(_WOFF, wb + need)
        if spins:
            self.stats["write_blocked_s"] += time.monotonic() - t_block
        self._count_write(len(data))

    def _try_publish_value(self, value: Any, tag: int, cd=None,
                           trace=None) -> Tuple[bool, bool]:
        """One encode attempt at the current write position.  Returns
        (published, blocked_on_reader): encoding straight into the ring
        means the payload size is unknown up front, so an overflow is
        disambiguated by WHAT bounded the window — the region tail
        (fixable by wrapping), the reader's position (fixable by
        waiting), or the whole ring (typed capacity error)."""
        wire = _wire_mod()
        cap = self.capacity
        wb = self._get(_WOFF)
        free = cap - (wb - self._get(_ROFF))
        wpos = wb % cap
        tail = cap - wpos
        window = min(tail, free)
        if window >= 16:
            try:
                n = wire.encode_into(
                    memoryview(self._mm)[
                        HEADER + wpos + 8 : HEADER + wpos + window - 4
                    ],
                    value,
                    tag,
                    trace,
                )
            except (struct.error, ValueError, IndexError):
                n = -1
            if n >= 0 and 8 + _align8(n + 4) <= window:
                crc = zlib.crc32(
                    memoryview(self._mm)[HEADER + wpos + 8 : HEADER + wpos + 8 + n]
                )
                if cd is not None:
                    crc = self._chaos_mutate(cd, wpos, n, crc)
                _U32C.pack_into(self._mm, HEADER + wpos + 8 + n, crc)
                _U64.pack_into(self._mm, HEADER + wpos, n)
                self._set(_WOFF, wb + 8 + _align8(n + 4))
                self._count_write(n)
                return True, False
        if window >= tail:
            # Tail-bounded: wrap (the tail is fully free) and retry.
            if tail >= cap - 16:
                # Full, empty ring couldn't hold it: genuinely too big.
                raise ChannelCapacityError(
                    f"value exceeds ring capacity {self.max_size} of "
                    f"{self.path}; raise the buffer size at compile time"
                )
            self._wrap(wb, tail)
            return False, False
        return False, True  # reader-bounded: wait for consumption

    def write_value(self, value: Any, tag: int = 0, timeout=DEFAULT_TIMEOUT) -> None:
        """Fast-path write: wire-encode ``value`` directly into the ring.

        A reader-bounded attempt partially ENCODES into the free window
        before discovering it doesn't fit, so the blocked loop must not
        re-attempt until the reader has actually consumed something — a
        parked writer of a large payload would otherwise burn a core
        re-encoding the same prefix every backoff wakeup (the podracer
        profile found runners spending >90% of parked CPU there)."""
        cd = _chaos_decide(self.path)
        if cd is not None and self._apply_write_chaos(cd, 0):
            return
        ts = _trace_begin()
        deadline = None  # resolved at first block (see write())
        spins = 0
        t_block = 0.0
        blocked_at_rb = None  # _ROFF snapshot taken BEFORE the blocked attempt
        while True:
            rb_before = self._get(_ROFF)
            if blocked_at_rb is not None:
                if rb_before == blocked_at_rb:
                    spins += 1
                    self._write_wait(spins, t_block, deadline)
                    continue
                blocked_at_rb = None
            published, blocked = self._try_publish_value(
                value, tag, cd, None if ts is None else _trace_trailer(ts)
            )
            if published:
                if spins:
                    self.stats["write_blocked_s"] += time.monotonic() - t_block
                if ts is not None:
                    _trace_commit_write(ts, self.kind, self.path)
                return
            if blocked:
                if spins == 0:
                    t_block = time.monotonic()
                    timeout = _resolve_timeout(timeout)
                    deadline = None if timeout is None else t_block + timeout
                # The pre-attempt snapshot is the race-safe anchor: a
                # reader advance DURING the attempt leaves _ROFF !=
                # rb_before, so the gate above retries immediately
                # instead of waiting on a ring the reader has already
                # drained (which would never advance again).
                blocked_at_rb = rb_before
                spins += 1
                self._write_wait(spins, t_block, deadline)

    def try_write_value(self, value: Any, tag: int = 0,
                        cd=_CHAOS_UNDECIDED, trace_state=None) -> bool:
        """Non-blocking write attempt (fan-out scheduling): False when
        the ring lacks free space right now.

        ``cd`` lets a fan-out scheduler pre-decide this frame's chaos
        verdict ONCE (pre-actions already applied) so blocked retries of
        the same frame don't consume extra rule match-ordinals — the
        seeded schedule must be deterministic per FRAME, not per retry
        (retry counts are timing-dependent).  ``trace_state`` is the
        frame's pre-minted _trace_begin state for the same reason: one
        write span per (frame, edge) no matter how many retries."""
        if self._closed_flag():
            raise ChannelClosed(self.path)
        if cd is _CHAOS_UNDECIDED:
            cd = _chaos_decide(self.path)
            if cd is not None and self._apply_write_chaos(cd, 0):
                return True
        if trace_state is None:
            trace_state = _trace_begin()
        while True:
            published, blocked = self._try_publish_value(
                value, tag, cd,
                None if trace_state is None else _trace_trailer(trace_state),
            )
            if published:
                if trace_state is not None:
                    _trace_commit_write(trace_state, self.kind, self.path)
                return True
            if blocked:
                return False
            # wrapped: retry immediately at the region start

    def close(self) -> None:
        """Drain-then-close: the reader sees ChannelClosed after
        consuming the backlog; blocked writers see it immediately.
        Either side may close (teardown path)."""
        try:
            self._tele_flush()
        except Exception:
            pass
        try:
            self._set(_COFF, 1)
        except ValueError:
            pass  # mmap already closed
        try:
            self._hdr.release()
        except Exception:
            pass
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass

    # -- reader ---------------------------------------------------------
    def _read_slot(self) -> Optional[Tuple[int, int]]:
        """(rpos, len) of the next record, advancing past wrap markers;
        None when the ring is empty.  An implausible record length (the
        torn-header signature of a writer killed mid-publish, or shm
        corruption) raises the typed corruption error WITHOUT advancing:
        the framing is unrecoverable from this position and the
        consumer's heavy recovery owns the edge."""
        cap = self.capacity
        while True:
            rb = self._get(_ROFF)
            if self._get(_WOFF) == rb:
                return None
            rpos = rb % cap
            tail = cap - rpos
            if tail < 8:
                self._set(_ROFF, rb + tail)
                continue
            n = _U64.unpack_from(self._mm, HEADER + rpos)[0]
            if n == WRAP:
                self._set(_ROFF, rb + tail)
                continue
            if n > self.max_size or 8 + _align8(n + 4) > tail:
                self._record_corruption()
                err = ChannelCorruptionError(
                    f"{self.path}: torn/garbage record length {n} at "
                    f"offset {rpos}"
                )
                err.advanced = False  # framing broken: no way past it
                raise err
            return rpos, n

    def _consume(self, rpos: int, n: int, blocked_since: float) -> None:
        self._set(_ROFF, self._get(_ROFF) + 8 + _align8(n + 4))
        s = self.stats
        s["reads"] += 1
        s["bytes_read"] += n
        if blocked_since:
            s["read_blocked_s"] += time.monotonic() - blocked_since
        self._tele_ops += 1
        if self._tele_ops >= self._TELE_FLUSH_OPS:
            self._tele_flush()

    def _read_wait(self, spins: int, t_block: float, deadline: Optional[float], timeout) -> None:
        if self._closed_flag():
            raise ChannelClosed(self.path)
        self._backoff(spins)
        if (
            deadline is not None
            and (spins >= 2000 or spins % 512 == 0)
            and time.monotonic() > deadline
        ):
            self.stats["read_timeouts"] += 1
            self.stats["read_blocked_s"] += time.monotonic() - t_block
            self._tele_flush()
            raise ChannelTimeout(f"no message on {self.path} within {timeout}s")

    def read(self, timeout=DEFAULT_TIMEOUT) -> bytes:
        deadline = None  # resolved at first block (see write())
        spins = 0
        t_block = 0.0
        while True:
            slot = self._read_slot()
            if slot is not None:
                rpos, n = slot
                blocked = t_block if spins else 0.0
                data = bytes(self._mm[HEADER + rpos + 8 : HEADER + rpos + 8 + n])
                stored = _U32C.unpack_from(self._mm, HEADER + rpos + 8 + n)[0]
                if zlib.crc32(data) != stored:
                    self._consume(rpos, n, blocked)
                    self._record_corruption()
                    raise ChannelCorruptionError(
                        f"{self.path}: CRC mismatch on {n}-byte record"
                    )
                self._consume(rpos, n, blocked)
                return data
            if spins == 0:
                t_block = time.monotonic()
                timeout = _resolve_timeout(timeout)
                deadline = None if timeout is None else t_block + timeout
            spins += 1
            self._read_wait(spins, t_block, deadline, timeout)

    def read_value(self, timeout=DEFAULT_TIMEOUT) -> Tuple[int, Any]:
        """Fast-path read: wire-decode straight from the ring; returns
        ``(tag, value)``.  Array payloads are copied out before the
        consume-ack (the writer reuses the region afterwards)."""
        return self.read_value_traced(timeout)[:2]

    def read_value_traced(self, timeout=DEFAULT_TIMEOUT) -> Tuple[int, Any, Any]:
        """``read_value`` plus the frame's trace context: ``(tag, value,
        tctx)`` where tctx is ``None`` for untraced frames or
        ``(trace_id, read_span_id, write_span_id)`` — the tuple a
        consumer hands to ``tracing.set_frame_context`` to re-parent its
        own spans under this hop."""
        wire = _wire_mod()
        deadline = None  # resolved at first block (see write())
        spins = 0
        t_block = 0.0
        while True:
            slot = self._read_slot()
            if slot is not None:
                rpos, n = slot
                blocked = t_block if spins else 0.0
                # ONE payload view serves both the CRC check and decode
                mv = memoryview(self._mm)[HEADER + rpos + 8 : HEADER + rpos + 8 + n]
                stored = _U32C.unpack_from(self._mm, HEADER + rpos + 8 + n)[0]
                if zlib.crc32(mv) != stored:
                    self._consume(rpos, n, blocked)
                    self._record_corruption()
                    raise ChannelCorruptionError(
                        f"{self.path}: CRC mismatch on {n}-byte record"
                    )
                try:
                    tag, value, tr = wire.decode_traced(mv, copy_arrays=True)
                except wire.WireFormatError as e:
                    self._consume(rpos, n, blocked)
                    self._record_corruption()
                    raise ChannelCorruptionError(
                        f"{self.path}: undecodable record ({e})"
                    ) from e
                self._consume(rpos, n, blocked)
                tctx = None if tr is None else _trace_read(tr, self.kind, self.path)
                return tag, value, tctx
            if spins == 0:
                t_block = time.monotonic()
                timeout = _resolve_timeout(timeout)
                deadline = None if timeout is None else t_block + timeout
            spins += 1
            self._read_wait(spins, t_block, deadline, timeout)

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Cross-host socket channels


_FRAME = struct.Struct("<Q")
_FRAME_HDR = struct.Struct("<QQ")  # payload len, seq
_ACK = b"\x01"
_MAGIC = b"RTPUCHN2"
_HELLO = struct.Struct("<8sQ16sQ")  # magic, epoch, token, writer sent_seq
_REPLY = struct.Struct("<8sQ16sQQ")  # magic, epoch, token, rx_seq, consumed


def _recv_exact_sock(sock, n: int) -> Optional[bytes]:
    """None on EOF; honors the socket's current timeout."""
    chunks = []
    while n:
        chunk = sock.recv(n)
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks) if len(chunks) != 1 else chunks[0]


class SocketListener:
    """One listening endpoint for one compiled edge.  The first accept
    pairs the edge (single-writer/single-reader contract); the listening
    socket then STAYS open so the paired writer can reattach after a
    connection-level failure by presenting the pairing token at a
    bumped epoch.  Unauthenticated or stale-epoch reconnects are
    rejected at the handshake and never reach the consumer."""

    def __init__(self):
        import socket as _socket

        self._sock = _socket.socket(_socket.AF_INET, _socket.SOCK_STREAM)
        self._sock.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
        self._sock.bind(("0.0.0.0", 0))
        self._sock.listen(4)
        self.port = self._sock.getsockname()[1]
        self.token = os.urandom(16)
        self.epoch = 0
        self._paired = False

    def accept(self, role: str, timeout: Optional[float] = 30.0) -> "SocketChannel":
        conn, epoch = self._accept_conn(timeout, rx_seq=0, consumed=0)
        return SocketChannel(conn, role, listener=self, epoch=epoch)

    def _accept_conn(self, timeout: Optional[float], rx_seq: int, consumed: int):
        """Accept + handshake one connection.  First pairing accepts
        epoch >= 1 from anyone; later connections must present this
        listener's token at an epoch strictly above the current one
        (the authenticated-reattach contract).  Rejected dials are
        closed and the accept loop continues until the deadline."""
        import socket as _socket

        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            remaining = None
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise ChannelTimeout(
                        f"no peer dialed listener :{self.port} within {timeout}s"
                    )
            self._sock.settimeout(remaining)
            try:
                conn, _peer = self._sock.accept()
            except _socket.timeout:
                raise ChannelTimeout(
                    f"no peer dialed listener :{self.port} within {timeout}s"
                ) from None
            except OSError:
                raise ChannelClosed(f"listener :{self.port} closed") from None
            try:
                # The handshake recv must not outlive the accept window:
                # an idle queued dial (stray scanner, rogue dial) sitting
                # first in the backlog would otherwise eat the whole
                # reattach budget before the authentic peer is examined.
                if deadline is not None:
                    conn.settimeout(
                        max(0.05, min(5.0, deadline - time.monotonic()))
                    )
                else:
                    conn.settimeout(5.0)
                hello = _recv_exact_sock(conn, _HELLO.size)
                if hello is None:
                    raise OSError("EOF during channel handshake")
                magic, epoch, token, _sent_seq = _HELLO.unpack(hello)
                ok = magic == _MAGIC and (
                    (not self._paired and epoch >= 1)
                    or (self._paired and token == self.token and epoch > self.epoch)
                )
                if not ok:
                    conn.close()
                    continue
                conn.sendall(_REPLY.pack(_MAGIC, epoch, self.token, rx_seq, consumed))
                conn.settimeout(None)
                conn.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
                self._paired = True
                self.epoch = int(epoch)
                return conn, int(epoch)
            except OSError:
                try:
                    conn.close()
                except OSError:
                    pass
                continue

    def close(self) -> None:
        try:
            self._sock.close()
        except OSError:
            pass


def dial(addr: Tuple[str, int], role: str, timeout: float = 15.0) -> "SocketChannel":
    """Connect to a bound listener; retries transient refusals on the
    unified CONNECT policy until ``timeout`` (listener startup races),
    then raises the typed ``ChannelConnectionError``.

    The pairing handshake is deliberately asynchronous: the hello frame
    is sent here, but the listener's reply is absorbed later from the
    ack stream — a graph with mutual socket edges would deadlock if
    every dial blocked on its reader reaching accept()."""
    import socket as _socket

    from ray_tpu._private import retry, telemetry

    assert role == "write", "channel listeners are reader-side by contract"
    bo = retry.CONNECT.start(deadline_s=timeout)
    last: Optional[Exception] = None
    while True:
        nd = _chaos_net_decide(tuple(addr))
        if nd is not None:
            if nd.delay_s > 0:
                time.sleep(nd.delay_s)
            if nd.drop:
                # A cut link refuses dials exactly like a dead listener:
                # retry on the CONNECT policy until heal or deadline.
                last = OSError("chaos net cut")
                delay = bo.next_delay()
                if delay is None:
                    telemetry.count_socket_connect("refused")
                    raise ChannelConnectionError(
                        f"socket channel endpoint {addr} refused ({last}); "
                        "the reader endpoint is gone — the edge must be "
                        "reattached from a live listener or rebuilt"
                    ) from last
                time.sleep(delay)
                continue
        try:
            sock = _socket.create_connection(tuple(addr), timeout=min(timeout, 5.0))
            try:
                sock.sendall(_HELLO.pack(_MAGIC, 1, bytes(16), 0))
            except OSError:
                sock.close()
                raise
            telemetry.count_socket_connect("ok")
            return SocketChannel(sock, role, peer_addr=tuple(addr))
        except OSError as e:
            last = e
            delay = bo.next_delay()
            if delay is None:
                telemetry.count_socket_connect("refused")
                raise ChannelConnectionError(
                    f"socket channel endpoint {addr} refused ({last}); "
                    "the reader endpoint is gone — the edge must be "
                    "reattached from a live listener or rebuilt"
                ) from last
            time.sleep(delay)


class SocketChannel:
    """The mmap ring's write/read/pending contract over one long-lived
    TCP connection (one per compiled REMOTE edge, chosen at compile time
    by placement).

    Data frames (``[u64 len][u64 seq][payload][u32 crc]``) flow
    writer→reader; one ack byte per *consumed* message flows back.
    Flow control is a bounded unacked window (like the ring's free
    space, widened to hide the network RTT); the unacked frames double
    as the bounded replay buffer for epoch reattach.  Reader-side: a
    daemonized reader thread validates CRC trailers and drains frames
    into a local queue so ``pending()`` is local and writer death (EOF /
    reset) is detected immediately as ``ChannelClosed`` — distinct from
    ``ChannelTimeout``, which means the peer is alive but silent.
    After a connection-level death either side can resume the session:
    the writer transparently re-dials (bounded, once per failed send)
    and the reader's consumer calls :func:`reattach`, which re-accepts
    at a bumped epoch and seq-resumes from the replay buffer."""

    kind = "socket"

    _CLOSED = object()  # poison frame received (orderly close)
    _DIED = object()  # EOF/reset without poison (peer death)
    _CORRUPT = object()  # CRC-mismatched frame (consumed as typed error)

    def __init__(self, sock, role: str, window: Optional[int] = None,
                 listener: Optional[SocketListener] = None,
                 peer_addr: Optional[Tuple[str, int]] = None,
                 epoch: int = 1):
        import collections
        import queue as _queue
        import socket as _socket
        import threading as _threading

        assert role in ("read", "write"), role
        if window is None:
            from ray_tpu._private.config import CONFIG

            window = int(getattr(CONFIG, "socket_channel_window", 8))
        self.role = role
        self.path = f"socket:{sock.getpeername()}"
        self._sock = sock
        # A dialed socket inherits create_connection's CONNECT timeout;
        # left in place it would make every later sendall of a frame
        # larger than the kernel buffers raise socket.timeout (read as
        # ChannelClosed) when the peer is slow to drain.  Steady-state
        # blocking is governed by the ack-window flow control, not a
        # per-syscall timeout.
        self._sock.settimeout(None)
        self._sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
        self._window = max(1, window)
        self._unacked = 0
        self._closed = False
        # -- epoch-reattach state --
        self.epoch = int(epoch)
        self._listener = listener  # read role: stays open for reattach
        self._peer_addr = peer_addr  # write role: re-dial target
        self._token: Optional[bytes] = listener.token if listener is not None else None
        # write role: the pairing reply (carrying the listener token)
        # arrives interleaved ahead of the ack stream; buffered here
        # until complete.
        self._reply_buf: Optional[bytes] = b"" if role == "write" else None
        self._sent_seq = 0  # frames transmitted (write role)
        self._acked_seq = 0  # frames consumed by the peer (write role)
        self._replay = collections.deque()  # (seq, frame bytes), unacked
        self._rx_seq = 0  # read role: highest seq enqueued
        self._consumed_seq = 0  # read role: frames delivered to consumer
        self._eof = None  # read role: death sentinel after rx exit
        self.stats = {
            "writes": 0,
            "reads": 0,
            "bytes_written": 0,
            "bytes_read": 0,
            "write_blocked_s": 0.0,
            "read_blocked_s": 0.0,
            "write_timeouts": 0,
            "read_timeouts": 0,
            "corruptions": 0,
        }
        self._tele_ops = 0
        self._tele_flushed = dict(self.stats)
        self._scratch = bytearray(64 * 1024)
        self._rx = None
        if role == "read":
            self._q: "_queue.Queue" = _queue.Queue()
            self._start_rx()

    def _start_rx(self) -> None:
        import threading as _threading

        self._rx = _threading.Thread(
            target=self._rx_loop, args=(self._sock,), daemon=True,
            name="socket-channel-rx",
        )
        self._rx.start()

    # Telemetry rides the SAME channel_* series as the ring (op labels
    # read/write) — one dataplane, two transports.
    _TELE_FLUSH_OPS = Channel._TELE_FLUSH_OPS
    _tele_flush = Channel._tele_flush
    _record_corruption = Channel._record_corruption

    # -- reader ---------------------------------------------------------
    def _rx_loop(self, sock) -> None:
        """Drains frames from ``sock`` (captured at thread start: a
        reattach swaps self._sock for a new connection and a new rx
        thread — this one must never read from it)."""
        while True:
            try:
                head = _recv_exact_sock(sock, 8)
                if head is None:
                    self._q.put(self._DIED)
                    return
                (n,) = _FRAME.unpack(head)
                if n == POISON:
                    self._q.put(self._CLOSED)
                    return
                seq_b = _recv_exact_sock(sock, 8)
                if seq_b is None:
                    self._q.put(self._DIED)
                    return
                (seq,) = _FRAME.unpack(seq_b)
                payload = _recv_exact_sock(sock, n)
                if payload is None:
                    self._q.put(self._DIED)
                    return
                crc_b = _recv_exact_sock(sock, 4)
                if crc_b is None:
                    self._q.put(self._DIED)
                    return
                if seq <= self._rx_seq:
                    continue  # replay duplicate after a reattach
                self._rx_seq = seq
                if zlib.crc32(payload) != _U32C.unpack(crc_b)[0]:
                    self._record_corruption()
                    self._q.put(self._CORRUPT)
                    continue
                self._q.put(payload)
            except OSError:
                self._q.put(self._DIED)
                return

    def _pop_frame(self, timeout: Optional[float]) -> bytes:
        import queue as _queue

        if self._eof is not None and self._q.empty():
            raise ChannelClosed(
                f"{self.path}: "
                + ("closed by writer" if self._eof is self._CLOSED else "writer died")
            )
        t0 = time.monotonic()
        try:
            item = self._q.get(timeout=timeout)
        except _queue.Empty:
            self.stats["read_timeouts"] += 1
            self.stats["read_blocked_s"] += time.monotonic() - t0
            self._tele_flush()
            raise ChannelTimeout(
                f"no message on {self.path} within {timeout}s"
            ) from None
        waited = time.monotonic() - t0
        if waited > 0.0005:
            self.stats["read_blocked_s"] += waited
        if item is self._CLOSED or item is self._DIED:
            # Remember the death so later reads fail the same way (until
            # a successful reattach clears it).
            self._eof = item
            raise ChannelClosed(
                f"{self.path}: "
                + ("closed by writer" if item is self._CLOSED else "writer died")
            )
        # Consume-ack: flow control counts messages the CONSUMER has
        # taken, not what the rx thread buffered.
        self._consumed_seq += 1
        try:
            self._sock.sendall(_ACK)
        except OSError:
            pass  # writer already gone; reads of buffered frames still valid
        if item is self._CORRUPT:
            raise ChannelCorruptionError(
                f"{self.path}: frame failed CRC validation"
            )
        s = self.stats
        s["reads"] += 1
        s["bytes_read"] += len(item)
        self._tele_ops += 1
        if self._tele_ops >= self._TELE_FLUSH_OPS:
            self._tele_flush()
        return item

    def read(self, timeout=DEFAULT_TIMEOUT) -> bytes:
        return self._pop_frame(_resolve_timeout(timeout))

    def read_value(self, timeout=DEFAULT_TIMEOUT) -> Tuple[int, Any]:
        return self.read_value_traced(timeout)[:2]

    def read_value_traced(self, timeout=DEFAULT_TIMEOUT) -> Tuple[int, Any, Any]:
        """(tag, value, tctx) — see Channel.read_value_traced."""
        wire = _wire_mod()
        frame = self._pop_frame(_resolve_timeout(timeout))
        try:
            # One-shot frame owned by us: arrays may alias it (no copy).
            tag, value, tr = wire.decode_traced(memoryview(frame), copy_arrays=False)
        except wire.WireFormatError as e:
            self._record_corruption()
            raise ChannelCorruptionError(
                f"{self.path}: undecodable frame ({e})"
            ) from e
        tctx = None if tr is None else _trace_read(tr, self.kind, self.path)
        return tag, value, tctx

    def pending(self) -> bool:
        if self.role == "read":
            return not self._q.empty()
        return self._unacked > 0

    # -- reattach -------------------------------------------------------
    def _reattach_read(self, timeout: float) -> bool:
        """Re-accept the writer's epoch-bumped dial on the still-open
        listener and resume the frame stream (the handshake reply tells
        the writer where to seq-resume from)."""
        ok = False
        try:
            if self._listener is None or self._eof is self._CLOSED:
                return False  # orderly close is final; only deaths reattach
            old_rx = self._rx
            conn, epoch = self._listener._accept_conn(
                timeout, rx_seq=self._rx_seq, consumed=self._consumed_seq
            )
            if old_rx is not None and old_rx.is_alive():
                old_rx.join(timeout=1.0)
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = conn
            self.epoch = epoch
            self._eof = None
            self._closed = False
            self._start_rx()
            ok = True
            return True
        except (ChannelTimeout, ChannelClosed, OSError):
            return False
        finally:
            _count_reattach(ok)

    def _reattach_write(self, timeout: float) -> bool:
        """Re-dial the reader's listener with the pairing token at a
        bumped epoch; the reply's rx_seq/consumed resync flow control
        and select which unacked frames to replay."""
        import socket as _socket

        ok = False
        try:
            if self._peer_addr is None:
                return False
            # The pairing reply may still sit in the dead socket's
            # receive buffer (delivered before the FIN): salvage it so
            # the token is known even when no ack was ever drained.
            if self._token is None:
                try:
                    self._sock.setblocking(False)
                    tail = self._sock.recv(4096)
                    if tail:
                        self._absorb_rx_bytes(tail)
                except OSError:
                    pass
            if self._token is None:
                return False
            try:
                self._sock.close()
            except OSError:
                pass
            nd = _chaos_net_decide(self._peer_addr)
            if nd is not None and nd.drop:
                raise OSError("chaos net cut")  # re-dial blocked by the partition
            sock = _socket.create_connection(self._peer_addr, timeout=min(timeout, 5.0))
            try:
                sock.settimeout(timeout)
                sock.sendall(
                    _HELLO.pack(_MAGIC, self.epoch + 1, self._token, self._sent_seq)
                )
                reply = _recv_exact_sock(sock, _REPLY.size)
                if reply is None:
                    raise OSError("EOF during reattach handshake")
                magic, epoch, _token, rx_seq, consumed = _REPLY.unpack(reply)
                if magic != _MAGIC or epoch != self.epoch + 1:
                    raise OSError("reattach handshake refused")
                sock.settimeout(None)
                sock.setsockopt(_socket.IPPROTO_TCP, _socket.TCP_NODELAY, 1)
            except OSError:
                sock.close()
                raise
            self.epoch = int(epoch)
            # Resync: acks lost with the connection are recovered from
            # the reader's consumed count; frames it never enqueued are
            # replayed (duplicates beyond rx_seq are dropped by seq).
            if consumed > self._acked_seq:
                self._ack_frames(consumed - self._acked_seq)
            self._sock = sock
            self._closed = False
            for seq, frame in self._replay:
                if seq > rx_seq:
                    self._sock.sendall(frame)
            ok = True
            return True
        except OSError:
            self._closed = True
            return False
        finally:
            _count_reattach(ok)

    # -- writer ---------------------------------------------------------
    def _ack_frames(self, n: int) -> None:
        self._acked_seq += n
        self._unacked = max(0, self._sent_seq - self._acked_seq)
        while self._replay and self._replay[0][0] <= self._acked_seq:
            self._replay.popleft()

    def _absorb_rx_bytes(self, data: bytes) -> None:
        """Writer-side rx stream: the pairing reply first (once), then
        one ack byte per frame the reader consumed."""
        if self._reply_buf is not None:
            take = _REPLY.size - len(self._reply_buf)
            self._reply_buf += data[:take]
            data = data[take:]
            if len(self._reply_buf) == _REPLY.size:
                magic, epoch, token, _rx, _cons = _REPLY.unpack(self._reply_buf)
                self._reply_buf = None
                if magic == _MAGIC:
                    self._token = bytes(token)
                    self.epoch = int(epoch)
        if data:
            self._ack_frames(len(data))

    def _await_reply(self, deadline: Optional[float]) -> None:
        """Block (bounded) until the pairing reply is absorbed.  Runs
        once, before the FIRST frame send: dial() deliberately does not
        wait for it (mutual-edge deadlock), but the reply must be in
        hand before any frame could need replaying — it carries the
        reattach token."""
        import select as _select

        while self._reply_buf is not None:
            timeout = 1.0
            if deadline is not None:
                timeout = deadline - time.monotonic()
                if timeout <= 0:
                    self.stats["write_timeouts"] += 1
                    raise ChannelTimeout(
                        f"pairing reply from {self.path} not received in time"
                    )
            try:
                ready, _, _ = _select.select([self._sock], [], [], timeout)
            except ValueError:
                raise OSError("socket closed") from None
            if not ready:
                continue
            data = self._sock.recv(4096)
            if not data:
                raise OSError("peer hung up before pairing reply")
            self._absorb_rx_bytes(data)

    def _drain_acks(self, deadline: Optional[float]) -> None:
        """Consume available acks; when the window is full, block (up to
        the deadline) for the next one."""
        import select as _select

        while True:
            timeout = 0.0
            if self._unacked >= self._window:
                if deadline is None:
                    timeout = 1.0
                else:
                    timeout = max(0.0, deadline - time.monotonic())
                    if timeout == 0.0:
                        self.stats["write_timeouts"] += 1
                        self._tele_flush()
                        raise ChannelTimeout(
                            f"reader of {self.path} did not consume "
                            f"(window {self._window} full)"
                        )
            try:
                ready, _, _ = _select.select([self._sock], [], [], timeout)
            except ValueError:  # closed fd: same meaning as a dead peer
                raise OSError("socket closed") from None
            if not ready:
                if self._unacked < self._window:
                    return
                continue  # window full: keep waiting for the ack
            acks = self._sock.recv(4096)
            if not acks:
                raise OSError("reader endpoint hung up")
            self._absorb_rx_bytes(acks)
            if self._unacked < self._window:
                return

    def _encode_scratch(self, value: Any, tag: int, trace=None) -> int:
        wire = _wire_mod()
        while True:
            try:
                return wire.encode_into(
                    memoryview(self._scratch)[_FRAME_HDR.size:len(self._scratch) - 4],
                    value, tag, trace,
                )
            except (struct.error, ValueError, IndexError):
                if len(self._scratch) >= 1 << 31:
                    raise ChannelCapacityError(
                        "value exceeds socket channel frame limit (2 GiB)"
                    ) from None
                self._scratch = bytearray(len(self._scratch) * 4)

    def _reattach_budget(self, deadline: Optional[float]) -> float:
        from ray_tpu._private.config import CONFIG

        budget = float(CONFIG.channel_reattach_timeout_s)
        if deadline is not None:
            budget = max(0.5, min(budget, deadline - time.monotonic()))
        return budget

    def _write_payload(self, value: Any, tag: int, timeout: Optional[float],
                       data: Optional[bytes], trace_state=None) -> None:
        if self._closed:
            raise ChannelClosed(self.path)
        ts = trace_state if trace_state is not None else _trace_begin()
        cd = _chaos_decide(self.path)
        if cd is not None:
            if cd.delay_s > 0:
                time.sleep(cd.delay_s)
            if cd.drop:
                self._count_write(len(data) if data is not None else 0)
                return
            if cd.close:
                # Abrupt connection loss (no poison): the send below
                # fails and takes the real reattach path — the drill
                # exercises exactly what a transient TCP drop does.
                try:
                    self._sock.close()
                except OSError:
                    pass
        nd = _chaos_net_decide(self._peer_addr)
        if nd is not None:
            if nd.delay_s > 0:
                time.sleep(nd.delay_s)
            if nd.drop:
                # A cut link looks like a dead connection to TCP: close
                # the socket so the send below takes the reattach path,
                # whose re-dial keeps failing through the same cut until
                # the link heals.
                try:
                    self._sock.close()
                except OSError:
                    pass
        deadline = None if timeout is None else time.monotonic() + timeout
        t0 = time.monotonic()
        # Encode the full frame once; it is also the replay entry.
        hdr = _FRAME_HDR.size
        if data is not None:
            n = len(data)
            if len(self._scratch) < hdr + n + 4:
                self._scratch = bytearray(hdr + n + 4)
            self._scratch[hdr : hdr + n] = data
            ts = None  # raw frames carry no wire header to trail
        else:
            n = self._encode_scratch(
                value, tag, None if ts is None else _trace_trailer(ts)
            )
        crc = zlib.crc32(memoryview(self._scratch)[hdr : hdr + n])
        if cd is not None and cd.corrupt:
            if n > 0:
                self._scratch[hdr] ^= 0xFF
            else:
                crc ^= 0xFFFFFFFF
        seq = self._sent_seq + 1
        _FRAME_HDR.pack_into(self._scratch, 0, n, seq)
        _U32C.pack_into(self._scratch, hdr + n, crc & 0xFFFFFFFF)
        frame = bytes(memoryview(self._scratch)[: hdr + n + 4])
        # Window space (one transparent reattach on a dead connection).
        for attempt in (0, 1):
            try:
                if self._reply_buf is not None:
                    self._await_reply(deadline)
                self._drain_acks(deadline)
                break
            except OSError:
                if attempt or not self._reattach_write(self._reattach_budget(deadline)):
                    self._closed = True
                    raise ChannelClosed(f"{self.path}: reader died") from None
        self._replay.append((seq, frame))
        self._sent_seq = seq
        self._unacked += 1
        try:
            if cd is not None and cd.torn:
                # Mid-frame writer kill: header + half the payload on
                # the wire, then the connection dies.
                self._sock.sendall(frame[: hdr + max(1, n // 2)])
                self._sock.close()
                raise OSError("chaos torn write")
            self._sock.sendall(frame)
        except OSError:
            if not self._reattach_write(self._reattach_budget(deadline)):
                # Never delivered and never will be: withdraw the frame.
                self._replay.pop()
                self._sent_seq -= 1
                self._unacked -= 1
                self._closed = True
                raise ChannelClosed(f"{self.path}: connection lost") from None
            # _reattach_write replayed every frame past the reader's
            # rx_seq — including this one.
        waited = time.monotonic() - t0
        if waited > 0.0005:
            self.stats["write_blocked_s"] += waited
        self._count_write(n)
        if ts is not None:
            _trace_commit_write(ts, self.kind, self.path)

    _count_write = Channel._count_write

    def write(self, data: bytes, timeout=DEFAULT_TIMEOUT) -> None:
        self._write_payload(None, 0, _resolve_timeout(timeout), data)

    def write_value(self, value: Any, tag: int = 0, timeout=DEFAULT_TIMEOUT) -> None:
        self._write_payload(value, tag, _resolve_timeout(timeout), None)

    def try_write_value(self, value: Any, tag: int = 0,
                        trace_state=None) -> bool:
        if self._closed:
            raise ChannelClosed(self.path)
        if self._unacked >= self._window:
            import select as _select

            try:
                ready, _, _ = _select.select([self._sock], [], [], 0.0)
            except ValueError:
                ready = []
            if ready:
                try:
                    acks = self._sock.recv(4096)
                except OSError:
                    acks = b""
                if not acks:
                    # Transient connection loss: the same transparent
                    # reattach the blocking write path gets — an edge
                    # write_value would heal must not tear down here —
                    # but bounded at 1 s, not the full reattach budget:
                    # try-writes are the fan-out scheduling primitive
                    # and independent sibling edges are stalled while
                    # this one re-dials.
                    if not self._reattach_write(
                        self._reattach_budget(time.monotonic() + 1.0)
                    ):
                        self._closed = True
                        raise ChannelClosed(f"{self.path}: reader died")
                    return False  # window/acks resynced; caller retries
                self._absorb_rx_bytes(acks)
            if self._unacked >= self._window:
                return False
        self._write_payload(value, tag, None, None, trace_state)
        return True

    # -- teardown -------------------------------------------------------
    def close(self) -> None:
        try:
            self._tele_flush()
        except Exception:
            pass
        if self.role == "write" and not self._closed:
            try:
                self._sock.sendall(_FRAME.pack(POISON))
            except OSError:
                pass
        self._closed = True
        try:
            self._sock.close()
        except OSError:
            pass
        if self._listener is not None:
            self._listener.close()

    def unlink(self) -> None:  # contract parity with the ring
        pass


def reattach(chan, timeout: Optional[float] = None) -> bool:
    """ONE shared recovery step for a channel that raised
    ``ChannelClosed``: returns True when the edge is live again (resume
    reading/writing), False when the peer is really gone and the
    caller's heavy recovery (replica evict + RPC fallback, runner
    respawn, pipeline restart) must run.  Socket endpoints perform the
    epoch-bumped reconnect with seq-resume; ring endpoints are only
    "reattachable" if the closed flag was never set (a local mmap
    failure), since a set flag means the peer deliberately closed.

    Counted via ``channel_reattach_total{result}``."""
    if timeout is None:
        from ray_tpu._private.config import CONFIG

        timeout = float(CONFIG.channel_reattach_timeout_s)
    try:
        if isinstance(chan, SocketChannel):
            if chan.role == "read":
                ok = chan._reattach_read(timeout)
            else:
                ok = chan._reattach_write(timeout)
            _trace_reattach(chan.path, ok, getattr(chan, "epoch", 0))
            return ok
        ok = False
        if isinstance(chan, Channel):
            ok = os.path.exists(chan.path) and not chan._closed_flag()
        _count_reattach(ok)
        _trace_reattach(chan.path, ok, getattr(chan, "epoch", 0))
        return ok
    except Exception:
        _count_reattach(False)
        _trace_reattach(getattr(chan, "path", "?"), False,
                        getattr(chan, "epoch", 0))
        return False


# ---------------------------------------------------------------------------
# Shared-memory fan-out: one writer, N same-node readers
#
# Broadcasting one payload to N co-located consumers (pipeline weight
# restore, activation/weight broadcast) previously cost N duplicate ring
# writes — N encodes and N payload copies through N rings.  A fan-out
# ring stores the payload ONCE; each reader owns a consume cursor, and
# the writer's free space is bounded by the SLOWEST reader (min over
# cursors), so flow control degrades exactly like a single-reader ring.
# Every reader registers its PID beside its cursor: a reader that dies
# without consuming (SIGKILL) is detected by the blocked writer and its
# cursor EVICTED, so a dead reader can no longer wedge the broadcast
# forever (counted via channel_fanout_evictions_total).
#
#     [wbytes u64][closed u64][n_readers u64][writer_pid u64]
#     [cursor0 u64]..[cursorN-1 u64][pid0 u64]..[pidN-1 u64][pad..64]
#     [ring payload: [u64 len][data][u32 crc][pad8] / WRAP markers ...]


_EVICTED_PID = (1 << 64) - 1


def _fanout_header(n_readers: int) -> int:
    return ((32 + 16 * n_readers + 63) // 64) * 64


class FanoutChannel:
    """Writer endpoint of a 1-to-N shm ring: write once, every reader
    consumes independently (N consume-acks)."""

    kind = "fanout"

    def __init__(self, path: str, n_readers: int,
                 max_size: int = 8 * 1024 * 1024, create: bool = False):
        if n_readers < 1:
            raise ValueError("fan-out channel needs at least one reader")
        self.path = path
        self.n_readers = n_readers
        header = _fanout_header(n_readers)
        if create:
            with open(path, "wb") as f:
                f.truncate(header + max_size)
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        self._header = header
        cap = size - header
        self.capacity = cap - (cap % 8)
        self.max_size = self.capacity - 24
        self._mm = mmap.mmap(self._f.fileno(), size)
        self._hdr = _u64_view(self._mm, header)
        if create:
            self._hdr[2] = n_readers
        else:
            stored = self._hdr[2]
            if stored != n_readers:
                raise ValueError(
                    f"fan-out channel {path} was created for {stored} "
                    f"readers, opened for {n_readers}"
                )
        self._hdr[3] = os.getpid()
        _register_shm_pid(path)
        self.stats = {"writes": 0, "bytes_written": 0, "write_blocked_s": 0.0,
                      "evictions": 0}

    def _cursor_off(self, idx: int) -> int:
        return 32 + 8 * idx

    def _pid_off(self, idx: int) -> int:
        return 32 + 8 * self.n_readers + 8 * idx

    def _min_read(self) -> int:
        """Free-space bound: min cursor over NON-evicted readers.  When
        every reader has been evicted the broadcast has no audience —
        typed close, never a silent write into the void."""
        lo = None
        for i in range(self.n_readers):
            if self._hdr[self._pid_off(i) >> 3] == _EVICTED_PID:
                continue
            cur = self._hdr[self._cursor_off(i) >> 3]
            lo = cur if lo is None or cur < lo else lo
        if lo is None:
            raise ChannelClosed(
                f"{self.path}: every fan-out reader is dead (evicted)"
            )
        return lo

    def _evict_dead_readers(self) -> int:
        """Evict readers whose registered PID is dead: their cursor no
        longer bounds the writer's free space.  A reader that never
        attached (pid slot 0) is NOT evicted — it may still be on its
        way; the write timeout covers that case exactly as before."""
        evicted = 0
        for i in range(self.n_readers):
            pid = self._hdr[self._pid_off(i) >> 3]
            if pid in (0, _EVICTED_PID) or _pid_alive(pid):
                continue
            self._hdr[self._pid_off(i) >> 3] = _EVICTED_PID
            evicted += 1
        if evicted:
            self.stats["evictions"] += evicted
            try:
                from ray_tpu._private import telemetry

                telemetry.count_fanout_eviction(evicted)
            except Exception:
                pass
        return evicted

    def write(self, data: bytes, timeout=DEFAULT_TIMEOUT) -> None:
        cd = _chaos_decide(self.path)
        if cd is not None:
            if cd.delay_s > 0:
                time.sleep(cd.delay_s)
            if cd.close:
                self.close()
                raise ChannelClosed(f"{self.path}: chaos close")
            if cd.drop:
                self.stats["writes"] += 1
                return
        need = 8 + _align8(len(data) + 4)
        if need > self.max_size:
            raise ChannelCapacityError(
                f"message of {len(data)} bytes exceeds fan-out channel "
                f"capacity {self.max_size}; raise the buffer size"
            )
        deadline = None  # resolved at first block (see Channel.write)
        spins = 0
        t_block = 0.0
        cap = self.capacity
        hdr = self._header
        while True:
            if self._hdr[1]:
                raise ChannelClosed(self.path)
            wb = self._hdr[0]
            free = cap - (wb - self._min_read())
            tail = cap - (wb % cap)
            if tail < need:
                if free >= tail:
                    # Wrap: the tail region is free for EVERY reader.
                    if tail >= 8:
                        _U64.pack_into(self._mm, hdr + (wb % cap), WRAP)
                    self._hdr[0] = wb + tail
                    continue
            elif free >= need:
                break
            if spins == 0:
                t_block = time.monotonic()
                timeout = _resolve_timeout(timeout)
                deadline = None if timeout is None else t_block + timeout
            spins += 1
            # A blocked broadcast probes for dead readers: a SIGKILLed
            # reader's un-advanced cursor must not wedge the writer for
            # the whole timeout (or forever, with timeout=None).
            if spins % 512 == 0 and self._evict_dead_readers():
                continue
            _poll_wait(spins - 1)
            if deadline is not None and time.monotonic() > deadline:
                self._evict_dead_readers()
                self.stats["write_blocked_s"] += time.monotonic() - t_block
                raise ChannelTimeout(
                    f"slowest of {self.n_readers} fan-out readers of "
                    f"{self.path} did not free ring space in time"
                )
        wpos = wb % cap
        self._mm[hdr + wpos + 8: hdr + wpos + 8 + len(data)] = data
        crc = zlib.crc32(data)
        if cd is not None:
            crc = _mutate_payload(self._mm, hdr + wpos + 8, len(data), crc, cd)
        _U32C.pack_into(self._mm, hdr + wpos + 8 + len(data), crc)
        _U64.pack_into(self._mm, hdr + wpos, len(data))
        self._hdr[0] = wb + need
        if spins:
            self.stats["write_blocked_s"] += time.monotonic() - t_block
        self.stats["writes"] += 1
        self.stats["bytes_written"] += len(data)

    def write_value(self, value: Any, tag: int = 0,
                    timeout=DEFAULT_TIMEOUT) -> None:
        """One encode, N consumers.  The broadcast path is not the
        per-microbatch hot loop, so the simple encode-then-copy beats
        duplicating the ring's in-place encoder for a third layout."""
        from ray_tpu._private import wire

        ts = _trace_begin()
        self.write(
            wire.encode(value, tag, None if ts is None else _trace_trailer(ts)),
            timeout=timeout,
        )
        if ts is not None:
            _trace_commit_write(ts, self.kind, self.path)

    def close(self) -> None:
        try:
            self._hdr[1] = 1
        except ValueError:
            pass
        try:
            self._hdr.release()
        except Exception:
            pass
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class FanoutReader:
    """Reader endpoint ``index`` of a :class:`FanoutChannel`: consumes
    every message exactly once at its own pace; advancing its cursor IS
    its consume-ack.  The reader registers its PID beside the cursor at
    open so a blocked writer can detect its death and evict it; an
    evicted reader that was NOT actually dead finds out typed (its pid
    slot is tombstoned) instead of silently losing frames."""

    kind = "fanout"

    def __init__(self, path: str, index: int):
        self.path = path
        self.index = index
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        self._mm = mmap.mmap(self._f.fileno(), size)
        n = _U64.unpack_from(self._mm, 16)[0]
        if not 0 <= index < n:
            raise ValueError(f"reader index {index} out of range (n={n})")
        self.n_readers = n
        self._header = _fanout_header(n)
        self._hdr = _u64_view(self._mm, self._header)
        cap = size - self._header
        self.capacity = cap - (cap % 8)
        self.max_size = self.capacity - 24
        self._off = 32 + 8 * index
        self._pid_slot = 32 + 8 * n + 8 * index
        self._hdr[self._pid_slot >> 3] = os.getpid()
        _register_shm_pid(path)
        self.stats = {"reads": 0, "bytes_read": 0, "read_blocked_s": 0.0,
                      "corruptions": 0}

    def pending(self) -> bool:
        try:
            return self._hdr[0] != self._hdr[self._off >> 3]
        except ValueError:
            return False

    def _check_evicted(self) -> None:
        if self._hdr[self._pid_slot >> 3] == _EVICTED_PID:
            raise ChannelClosed(
                f"{self.path}: reader {self.index} was evicted (writer "
                f"presumed this PID dead)"
            )

    def _next_slot(self) -> Optional[Tuple[int, int]]:
        cap = self.capacity
        while True:
            rb = self._hdr[self._off >> 3]
            if self._hdr[0] == rb:
                return None
            rpos = rb % cap
            tail = cap - rpos
            if tail < 8:
                self._hdr[self._off >> 3] = rb + tail
                continue
            n = _U64.unpack_from(self._mm, self._header + rpos)[0]
            if n == WRAP:
                self._hdr[self._off >> 3] = rb + tail
                continue
            if n > self.max_size or 8 + _align8(n + 4) > tail:
                self.stats["corruptions"] += 1
                _count_corruption()
                err = ChannelCorruptionError(
                    f"{self.path}: torn/garbage fan-out record length {n}"
                )
                err.advanced = False  # framing broken: no way past it
                raise err
            return rpos, n

    def read(self, timeout=DEFAULT_TIMEOUT) -> bytes:
        deadline = None  # resolved at first block (see write())
        spins = 0
        t_block = 0.0
        while True:
            # Eviction outranks everything: once the writer tombstoned
            # this cursor it may have overwritten the unread region, so
            # interpreting it would misreport corruption.
            self._check_evicted()
            slot = self._next_slot()
            if slot is not None:
                rpos, n = slot
                data = bytes(
                    self._mm[self._header + rpos + 8: self._header + rpos + 8 + n]
                )
                stored = _U32C.unpack_from(self._mm, self._header + rpos + 8 + n)[0]
                rb = self._hdr[self._off >> 3]
                self._hdr[self._off >> 3] = rb + 8 + _align8(n + 4)
                if zlib.crc32(data) != stored:
                    self.stats["corruptions"] += 1
                    _count_corruption()
                    raise ChannelCorruptionError(
                        f"{self.path}: fan-out record failed CRC validation"
                    )
                self.stats["reads"] += 1
                self.stats["bytes_read"] += n
                if spins:
                    self.stats["read_blocked_s"] += time.monotonic() - t_block
                return data
            if self._hdr[1]:
                raise ChannelClosed(self.path)
            if spins == 0:
                t_block = time.monotonic()
                timeout = _resolve_timeout(timeout)
                deadline = None if timeout is None else t_block + timeout
            spins += 1
            _poll_wait(spins - 1)
            if deadline is not None and time.monotonic() > deadline:
                self.stats["read_blocked_s"] += time.monotonic() - t_block
                raise ChannelTimeout(
                    f"no fan-out message on {self.path} within {timeout}s"
                )

    def read_value(self, timeout=DEFAULT_TIMEOUT) -> Tuple[int, Any]:
        return self.read_value_traced(timeout)[:2]

    def read_value_traced(self, timeout=DEFAULT_TIMEOUT) -> Tuple[int, Any, Any]:
        """(tag, value, tctx) — see Channel.read_value_traced."""
        from ray_tpu._private import wire

        # The frame was copied out of the ring by read(); arrays may
        # alias the private copy.
        try:
            tag, value, tr = wire.decode_traced(
                memoryview(self.read(timeout)), copy_arrays=False
            )
        except wire.WireFormatError as e:
            self.stats["corruptions"] += 1
            _count_corruption()
            raise ChannelCorruptionError(
                f"{self.path}: undecodable fan-out record ({e})"
            ) from e
        tctx = None if tr is None else _trace_read(tr, self.kind, self.path)
        return tag, value, tctx

    def close(self) -> None:
        try:
            self._hdr.release()
        except Exception:
            pass
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


# ---------------------------------------------------------------------------
# Compile-time endpoint plumbing


# Listeners bound during a compiled graph's setup phase, consumed when
# its resident loop (or the driver) opens the read side.  Keyed by
# (dag token, channel id); same process between setup and loop start.
_BOUND_LISTENERS: dict = {}


def bind_listener(token: str, cid: str) -> int:
    lst = SocketListener()
    _BOUND_LISTENERS[(token, cid)] = lst
    return lst.port


def take_listener(token: str, cid: str) -> SocketListener:
    return _BOUND_LISTENERS.pop((token, cid))


def drop_listeners(token: str) -> None:
    for key in [k for k in _BOUND_LISTENERS if k[0] == token]:
        _BOUND_LISTENERS.pop(key).close()


def ring_base_dir() -> str:
    """Filesystem base for ring-channel files: tmpfs when available.
    The single place that picks it — compiled-DAG and serve ring
    directories must land on the same filesystem."""
    import tempfile

    return "/dev/shm" if os.path.isdir("/dev/shm") else tempfile.gettempdir()


def sweep_orphan_ring_dirs(base: Optional[str] = None,
                           grace_s: Optional[float] = None) -> int:
    """Reclaim ring/fan-out shm directories whose registered owner PIDs
    are ALL dead (the tmpfs leak after a SIGKILL skipped every teardown
    path).  Run by the raylet on a channel_shm_sweep_period_s cadence;
    safe to run from multiple raylets of one host (unlink succeeds once,
    so files are never double-counted).  Conservative by construction:
    a directory with no PID registry yet, or any live registered owner,
    is never touched, and directories younger than the grace window are
    skipped (the mkdir→first-open registration gap).

    Returns the number of channel files reclaimed (counted via
    ``channel_shm_reclaimed_total``)."""
    from ray_tpu._private.config import CONFIG

    if base is None:
        base = ring_base_dir()
    if grace_s is None:
        grace_s = float(CONFIG.channel_shm_orphan_grace_s)
    reclaimed = 0
    try:
        names = os.listdir(base)
    except OSError:
        return 0
    now = time.time()
    for name in names:
        if not name.startswith("ray_tpu_"):
            continue
        d = os.path.join(base, name)
        try:
            if not os.path.isdir(d) or now - os.stat(d).st_mtime < grace_s:
                continue
            with open(os.path.join(d, ".pids")) as f:
                pids = {int(line) for line in f if line.strip()}
        except (OSError, ValueError):
            continue  # no/invalid registry: conservative, skip
        if not pids or any(_pid_alive(p) for p in pids):
            continue
        # Narrow the attach race: a process registering between the
        # first read and the unlink below would lose its live files.
        # Creating channel files bumps the dir mtime (grace-protected),
        # but pure-open endpoints only append to .pids — re-read it
        # immediately before destruction so the window shrinks from one
        # sweep period to microseconds.
        try:
            with open(os.path.join(d, ".pids")) as f:
                pids2 = {int(line) for line in f if line.strip()}
        except (OSError, ValueError):
            continue
        if pids2 != pids and any(_pid_alive(p) for p in pids2):
            continue
        try:
            entries = os.listdir(d)
        except OSError:
            continue
        for fn in entries:
            try:
                os.unlink(os.path.join(d, fn))
                if fn != ".pids":
                    reclaimed += 1
            except OSError:
                pass
        try:
            os.rmdir(d)
        except OSError:
            pass
    if reclaimed:
        try:
            from ray_tpu._private import telemetry

            telemetry.count_shm_reclaimed(reclaimed)
        except Exception:
            pass
    return reclaimed


def node_hosts(worker) -> dict:
    """node id (hex) -> reachable host, from the GCS cluster view.
    Local (unix-socket) raylets are same-machine by definition."""
    from ray_tpu._private.ids import NodeID

    info = worker.gcs_client.call("get_cluster_info")
    hosts = {}
    for n in info["nodes"].values():
        addr = str(n.get("raylet_address", ""))
        if addr.startswith("unix:") or ":" not in addr:
            host = "127.0.0.1"
        else:
            host = addr.rsplit(":", 1)[0] or "127.0.0.1"
        if host == "0.0.0.0":
            host = "127.0.0.1"
        hosts[NodeID(n["node_id"]).hex()] = host
    return hosts


def open_channel(desc: dict, role: str, timeout: float = 30.0):
    """Open one endpoint of a planned channel.

    ``desc`` is the compile-time descriptor: ``{"kind": "ring", "path"}``
    or ``{"kind": "socket", "token", "id", "addr": (host, port)}``.
    Socket rule: the READER bound the listener during setup (and accepts
    here); the WRITER dials.  Dials never deadlock accepts because every
    listener is bound before any loop starts (TCP completes the
    handshake from the backlog; the pairing reply is absorbed lazily
    from the ack stream)."""
    if desc["kind"] == "ring":
        return Channel(desc["path"])
    if role == "write":
        return dial(tuple(desc["addr"]), "write", timeout=timeout)
    return take_listener(desc["token"], desc["id"]).accept("read", timeout=timeout)


def write_value_fanout(
    targets: Sequence[Tuple[Any, Any, int]], timeout=DEFAULT_TIMEOUT
) -> None:
    """Write a batch of (channel, value, tag) with fan-out overlap: each
    blocked edge is retried round-robin via ``try_write_value`` so one
    slow consumer never head-of-line-blocks an independent branch (the
    graph-level scheduling rule: issue every fan-out write before
    blocking on any single peer)."""
    if len(targets) == 1:
        chan, value, tag = targets[0]
        chan.write_value(value, tag, timeout=timeout)  # resolves lazily
        return
    # Ring frames get their chaos verdict HERE, once per frame, with the
    # pre-actions (drop / delay / close) applied exactly once — blocked
    # retry rounds below must not consume extra match ordinals or
    # re-sleep a delay (seeded schedules are per-frame deterministic).
    # Socket channels decide inside the actual send, which try-writes
    # reach at most once per frame.
    pending = []
    for chan, value, tag in targets:
        cd = _CHAOS_UNDECIDED
        if isinstance(chan, Channel):
            cd = _chaos_decide(chan.path)
            if cd is not None and chan._apply_write_chaos(cd, 0):
                continue  # dropped: the frame silently vanishes
        # One trace state per (frame, target): each edge gets its own
        # write span, and blocked retry rounds reuse the same span id.
        pending.append((chan, value, tag, cd, _trace_begin()))
    deadline = None  # resolved at first blocked round (see Channel.write)
    spins = 0
    while pending:
        rest = []
        for chan, value, tag, cd, ts in pending:
            if cd is _CHAOS_UNDECIDED:
                ok = chan.try_write_value(value, tag, trace_state=ts)
            else:
                ok = chan.try_write_value(value, tag, cd=cd, trace_state=ts)
            if not ok:
                rest.append((chan, value, tag, cd, ts))
        if not rest:
            return
        pending = rest
        if spins == 0:
            timeout = _resolve_timeout(timeout)
            deadline = None if timeout is None else time.monotonic() + timeout
        spins += 1
        _poll_wait(spins - 1)
        if deadline is not None and time.monotonic() > deadline:
            raise ChannelTimeout(
                f"{len(pending)} fan-out peers did not consume within {timeout}s"
            )
