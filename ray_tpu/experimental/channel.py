"""Mutable shared-memory channels for compiled DAGs.

Reference: src/ray/core_worker/experimental_mutable_object_manager.h:48
and python/ray/experimental/channel/shared_memory_channel.py — a
fixed-size buffer written in place per execution instead of allocating
a new object in the store per message.

Single-writer / single-reader, same host.  Layout of the mmap'd file:

    [seq u64][ack u64][len u64][pad u64][payload ...]

Seqlock protocol: the writer waits for ``ack == seq`` (previous message
consumed — flow control), bumps ``seq`` to odd, writes len+payload,
then bumps ``seq`` to the next even value.  The reader waits for an
even ``seq`` it hasn't consumed, copies the payload, re-checks ``seq``
(torn-read guard), and publishes ``ack = seq``.  A length of 2**64-1 is
the poison pill: the channel is closed and readers raise ChannelClosed.
"""

from __future__ import annotations

import mmap
import os
import struct
import time
from typing import Optional

_U64 = struct.Struct("<Q")
HEADER = 32
POISON = (1 << 64) - 1


class ChannelClosed(Exception):
    pass


class ChannelTimeout(Exception):
    pass


class Channel:
    @staticmethod
    def create_file(path: str, max_size: int = 8 * 1024 * 1024) -> None:
        """Allocate a channel's backing file without opening an endpoint
        (the single place that knows the on-disk layout)."""
        with open(path, "wb") as f:
            f.truncate(HEADER + max_size)

    def __init__(self, path: str, max_size: int = 8 * 1024 * 1024, create: bool = False):
        self.path = path
        self.max_size = max_size
        if create:
            with open(path, "wb") as f:
                f.truncate(HEADER + max_size)
        # Open by both sides; size from the file (reader may not know).
        self._f = open(path, "r+b")
        size = os.fstat(self._f.fileno()).st_size
        self.max_size = size - HEADER
        self._mm = mmap.mmap(self._f.fileno(), size)
        self._last_read = 0
        # Dataplane counters (item-2 hot path must land measurable):
        # plain dict increments on the fast path (~100 ns), folded into
        # telemetry in batches of _TELE_FLUSH_OPS so per-op cost stays
        # far inside the <5% budget at channel rates.
        self.stats = {
            "writes": 0,
            "reads": 0,
            "bytes_written": 0,
            "bytes_read": 0,
            "write_blocked_s": 0.0,
            "read_blocked_s": 0.0,
            "write_timeouts": 0,
            "read_timeouts": 0,
        }
        self._tele_ops = 0
        self._tele_flushed = dict(self.stats)

    # -- raw fields -----------------------------------------------------
    def _get(self, off: int) -> int:
        return _U64.unpack_from(self._mm, off)[0]

    def _set(self, off: int, v: int) -> None:
        _U64.pack_into(self._mm, off, v)

    # Hot-spinning only helps when the peer can run on another core;
    # on a 1-2 core host it starves the peer for a whole scheduler
    # quantum (~1 ms RTT).  sched_yield-first is ~10x faster there and
    # within noise on big hosts.
    _HOT_SPINS = 1500 if (os.cpu_count() or 1) > 2 else 0

    def _backoff(self, spins: int) -> None:
        """Latency-first wait: (multicore only) hot-spin ~0.1ms, then
        sched_yield, then ramp sleeps toward 1ms so a long-idle resident
        loop doesn't pin a core (the reference's channels busy-wait the
        same way)."""
        if spins < self._HOT_SPINS:
            return
        if spins < self._HOT_SPINS + 4000:
            time.sleep(0)
            return
        time.sleep(min(0.001, 0.00002 * (spins - self._HOT_SPINS - 3999)))

    _TELE_FLUSH_OPS = 512

    def _tele_flush(self) -> None:
        """Push counter deltas since the last flush to telemetry (one
        batched inc per series); called every _TELE_FLUSH_OPS ops, on
        timeout, and on close."""
        from ray_tpu._private import telemetry

        s, last = self.stats, self._tele_flushed
        telemetry.count_channel_ops("write", s["writes"] - last["writes"])
        telemetry.count_channel_ops("read", s["reads"] - last["reads"])
        telemetry.add_channel_blocked(
            "write", s["write_blocked_s"] - last["write_blocked_s"]
        )
        telemetry.add_channel_blocked(
            "read", s["read_blocked_s"] - last["read_blocked_s"]
        )
        telemetry.count_channel_timeout(
            "write", s["write_timeouts"] - last["write_timeouts"]
        )
        telemetry.count_channel_timeout(
            "read", s["read_timeouts"] - last["read_timeouts"]
        )
        self._tele_flushed = dict(s)
        self._tele_ops = 0

    def pending(self) -> bool:
        """Occupancy: a published message the reader hasn't acked yet."""
        try:
            return self._get(8) != self._get(0)
        except ValueError:
            return False  # mmap closed

    # -- writer ---------------------------------------------------------
    def write(self, data: bytes, timeout: Optional[float] = 30.0) -> None:
        if len(data) > self.max_size:
            raise ValueError(
                f"message of {len(data)} bytes exceeds channel capacity "
                f"{self.max_size}; raise max_size at compile time"
            )
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        while self._get(8) != self._get(0):  # previous not yet consumed
            if spins == 0:
                t_block = time.monotonic()
            spins += 1
            self._backoff(spins)
            if deadline is not None and (spins >= 2000 or spins % 512 == 0) and time.monotonic() > deadline:
                self.stats["write_timeouts"] += 1
                self.stats["write_blocked_s"] += time.monotonic() - t_block
                self._tele_flush()
                raise ChannelTimeout(f"reader of {self.path} did not consume in {timeout}s")
        seq = self._get(0)
        self._set(0, seq + 1)  # odd: write in progress
        self._set(16, len(data))
        self._mm[HEADER : HEADER + len(data)] = data
        self._set(0, seq + 2)  # even: published
        s = self.stats
        s["writes"] += 1
        s["bytes_written"] += len(data)
        if spins:
            s["write_blocked_s"] += time.monotonic() - t_block
        self._tele_ops += 1
        if self._tele_ops >= self._TELE_FLUSH_OPS:
            self._tele_flush()

    def close(self) -> None:
        """Poison the channel: the reader's next read raises
        ChannelClosed.  Does not wait for ack (teardown path)."""
        try:
            self._tele_flush()
        except Exception:
            pass
        try:
            seq = self._get(0)
            self._set(0, seq + 1 if seq % 2 == 0 else seq)
            self._set(16, POISON)
            self._set(0, (seq // 2) * 2 + 2)
        except ValueError:
            pass  # mmap already closed
        try:
            self._mm.close()
            self._f.close()
        except Exception:
            pass

    # -- reader ---------------------------------------------------------
    def read(self, timeout: Optional[float] = 30.0) -> bytes:
        deadline = None if timeout is None else time.monotonic() + timeout
        spins = 0
        t_block = 0.0
        while True:
            seq = self._get(0)
            if seq % 2 == 0 and seq != self._last_read:
                n = self._get(16)
                if n == POISON:
                    raise ChannelClosed(self.path)
                data = bytes(self._mm[HEADER : HEADER + n])
                if self._get(0) == seq:  # not torn
                    self._last_read = seq
                    self._set(8, seq)  # ack: writer may proceed
                    s = self.stats
                    s["reads"] += 1
                    s["bytes_read"] += len(data)
                    if spins:
                        s["read_blocked_s"] += time.monotonic() - t_block
                    self._tele_ops += 1
                    if self._tele_ops >= self._TELE_FLUSH_OPS:
                        self._tele_flush()
                    return data
            if spins == 0:
                t_block = time.monotonic()
            spins += 1
            self._backoff(spins)
            if deadline is not None and (spins >= 2000 or spins % 512 == 0) and time.monotonic() > deadline:
                self.stats["read_timeouts"] += 1
                self.stats["read_blocked_s"] += time.monotonic() - t_block
                self._tele_flush()
                raise ChannelTimeout(f"no message on {self.path} within {timeout}s")

    def unlink(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass
