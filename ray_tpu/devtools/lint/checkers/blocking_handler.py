"""blocking-in-handler: no blocking calls on RPC dispatch / pubsub threads.

Two kinds of latency-critical entry points exist in this codebase
(``_private/rpc.py``):

- **server handlers** — ``async def rpc_<method>`` / ``push_<method>``
  coroutines dispatched by RpcServer on the process's asyncio loop.  A
  ``time.sleep`` (or blocking socket read) there freezes the *entire*
  event loop: every other RPC this process serves stalls behind it.
  (``await asyncio.sleep`` is fine.)
- **client push/close callbacks** — functions wired via ``on_push=`` /
  ``on_close=`` / ``on_reconnect=`` (GCS pubsub deliveries among them)
  run on the RpcClient's reader thread.  Blocking there stalls every
  in-flight reply on that connection — the PR 1 GCS-restart bug class
  (blocking GCS pushes stalled stream consumption through outages).

The checker collects those entry points per module, builds a
module-local call graph (``self.method()`` and module-level ``func()``
edges), and flags ``time.sleep`` / blocking ``recv`` reachable within
the module.  Cross-module reachability is out of scope by design — a
blocking call behind an import boundary needs its own local entry point
to be flagged, which keeps the analysis fast and the findings precise.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.core import Module, Violation, call_name

name = "blocking-in-handler"

_CALLBACK_KWARGS = ("on_push", "on_close", "on_reconnect", "on_disconnect")
_MAX_DEPTH = 8


def _blocking(node: ast.Call, in_async: bool) -> Optional[str]:
    cn = call_name(node)
    if cn in ("time.sleep", "_time.sleep"):
        if node.args and isinstance(node.args[0], ast.Constant) and \
                node.args[0].value == 0:
            return None
        return "time.sleep"
    if cn.endswith(".recv") or cn.endswith("_recv_exact") or cn.endswith("_recv_msg"):
        return "blocking socket recv"
    if cn.endswith(".accept") and "listener" in cn:
        return "blocking socket accept"
    return None


def _fn_index(mod: Module) -> Dict[str, ast.AST]:
    return {q: fn for q, fn in mod.iter_functions()}


def _own_nodes(fn: ast.AST):
    """Nodes in ``fn``'s own body, pruning nested function/lambda bodies —
    a closure defined in a handler (e.g. a thread target) does not run on
    the handler's thread, so its blocking calls are not the handler's."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        n = todo.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        todo.extend(ast.iter_child_nodes(n))


def _entries(mod: Module, fns: Dict[str, ast.AST]) -> List[str]:
    out: List[str] = []
    for q, fn in fns.items():
        base = q.split(".")[-1]
        if isinstance(fn, ast.AsyncFunctionDef) and (
            base.startswith("rpc_") or base.startswith("push_")
        ):
            out.append(q)
    # Callbacks passed as on_push=self._x / on_close=self._x, as
    # `client.on_push = self._x` assignments, or inside lambdas.
    for node in ast.walk(mod.tree):
        refs: List[ast.AST] = []
        if isinstance(node, ast.Call):
            refs = [kw.value for kw in node.keywords if kw.arg in _CALLBACK_KWARGS]
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and t.attr in _CALLBACK_KWARGS:
                refs = [node.value]
        for ref in refs:
            for target in _callback_targets(ref):
                # Resolve the attr name to any class method in this module.
                for q in fns:
                    if q.split(".")[-1] == target:
                        out.append(q)
    return sorted(set(out))


def _callback_targets(ref: ast.AST) -> List[str]:
    """Method names referenced by a callback expression: `self._x`,
    `lambda ...: self._x(...)`, or a bare function name."""
    if isinstance(ref, ast.Attribute):
        return [ref.attr]
    if isinstance(ref, ast.Name):
        return [ref.id]
    if isinstance(ref, ast.Lambda):
        return [
            call_name(c).split(".")[-1]
            for c in ast.walk(ref.body)
            if isinstance(c, ast.Call)
        ]
    return []


def _callees(mod: Module, q: str, fn: ast.AST, fns: Dict[str, ast.AST]) -> Set[str]:
    cls = q.split(".")[0] if "." in q else None
    out: Set[str] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        if cn.startswith("self.") and cls:
            cand = f"{cls}.{cn[5:]}"
            if cand in fns:
                out.add(cand)
        elif "." not in cn and cn in fns:
            out.add(cn)
    return out


def check(mod: Module) -> Iterable[Violation]:
    fns = _fn_index(mod)
    if not fns:
        return []
    entries = _entries(mod, fns)
    if not entries:
        return []
    out: List[Violation] = []
    reported: Set[Tuple[str, int]] = set()
    for entry in entries:
        # BFS through the module-local call graph.
        seen = {entry}
        frontier: List[Tuple[str, Tuple[str, ...]]] = [(entry, (entry,))]
        depth = 0
        while frontier and depth < _MAX_DEPTH:
            nxt: List[Tuple[str, Tuple[str, ...]]] = []
            for q, trail in frontier:
                fn = fns[q]
                in_async = isinstance(fn, ast.AsyncFunctionDef)
                for node in _own_nodes(fn):
                    if isinstance(node, ast.Call):
                        kind = _blocking(node, in_async)
                        if kind and (q, node.lineno) not in reported:
                            reported.add((q, node.lineno))
                            via = (
                                "" if len(trail) == 1
                                else " via " + " -> ".join(trail[1:])
                            )
                            out.append(
                                Violation(
                                    check=name,
                                    path=mod.relpath,
                                    line=node.lineno,
                                    symbol=q,
                                    tag=f"{kind}@{entry}",
                                    message=(
                                        f"{kind} reachable from handler/pubsub "
                                        f"entry point {entry}{via} — this blocks "
                                        "the RPC dispatch loop / reader thread; "
                                        "defer to a worker thread or use "
                                        "asyncio.sleep in async handlers"
                                    ),
                                )
                            )
                for callee in _callees(mod, q, fn, fns):
                    if callee not in seen:
                        seen.add(callee)
                        nxt.append((callee, trail + (callee,)))
            frontier = nxt
            depth += 1
    return out
