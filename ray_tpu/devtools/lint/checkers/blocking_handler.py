"""blocking-in-handler: no blocking calls on RPC dispatch / pubsub threads.

Two kinds of latency-critical entry points exist in this codebase
(``_private/rpc.py``):

- **server handlers** — ``async def rpc_<method>`` / ``push_<method>``
  coroutines dispatched by RpcServer on the process's asyncio loop.  A
  ``time.sleep`` (or blocking socket read) there freezes the *entire*
  event loop: every other RPC this process serves stalls behind it.
  (``await asyncio.sleep`` is fine.)
- **client push/close callbacks** — functions wired via ``on_push=`` /
  ``on_close=`` / ``on_reconnect=`` (GCS pubsub deliveries among them)
  run on the RpcClient's reader thread.  Blocking there stalls every
  in-flight reply on that connection — the PR 1 GCS-restart bug class
  (blocking GCS pushes stalled stream consumption through outages).

The checker collects those entry points per module, builds a
**cross-module call graph**, and flags ``time.sleep`` / blocking
``recv`` reachable from any entry point.  Edges resolved:

- ``self.method()`` within the entry's class and bare ``func()`` within
  the module (as before);
- ``alias.func()`` where ``alias`` imports another module in the linted
  tree (``from ray_tpu._private import rpc`` → ``rpc.call_idempotent``
  lands in rpc.py's ``call_idempotent``) — the PR 5 follow-up: blocking
  calls reached *through helper modules* used to escape the analysis;
- ``alias.Class(...)`` constructor calls → ``Class.__init__`` in the
  target module.

Method calls on arbitrary objects stay unresolved by design (no type
inference); depth is bounded by ``_MAX_DEPTH``.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.core import Module, Project, Violation, call_name

name = "blocking-in-handler"

_CALLBACK_KWARGS = ("on_push", "on_close", "on_reconnect", "on_disconnect")
_MAX_DEPTH = 8

# (relpath, qualname) node in the cross-module call graph
_Node = Tuple[str, str]


def _blocking(node: ast.Call, in_async: bool) -> Optional[str]:
    cn = call_name(node)
    if cn in ("time.sleep", "_time.sleep"):
        if node.args and isinstance(node.args[0], ast.Constant) and \
                node.args[0].value == 0:
            return None
        return "time.sleep"
    if cn.endswith(".recv") or cn.endswith("_recv_exact") or cn.endswith("_recv_msg"):
        return "blocking socket recv"
    if cn.endswith(".accept") and "listener" in cn:
        return "blocking socket accept"
    return None


def _fn_index(mod: Module) -> Dict[str, ast.AST]:
    return {q: fn for q, fn in mod.iter_functions()}


def _own_nodes(fn: ast.AST):
    """Nodes in ``fn``'s own body, pruning nested function/lambda bodies —
    a closure defined in a handler (e.g. a thread target) does not run on
    the handler's thread, so its blocking calls are not the handler's."""
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        n = todo.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        todo.extend(ast.iter_child_nodes(n))


def _entries(mod: Module, fns: Dict[str, ast.AST]) -> List[str]:
    out: List[str] = []
    for q, fn in fns.items():
        base = q.split(".")[-1]
        if isinstance(fn, ast.AsyncFunctionDef) and (
            base.startswith("rpc_") or base.startswith("push_")
        ):
            out.append(q)
    # Callbacks passed as on_push=self._x / on_close=self._x, as
    # `client.on_push = self._x` assignments, or inside lambdas.
    for node in ast.walk(mod.tree):
        refs: List[ast.AST] = []
        if isinstance(node, ast.Call):
            refs = [kw.value for kw in node.keywords if kw.arg in _CALLBACK_KWARGS]
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and t.attr in _CALLBACK_KWARGS:
                refs = [node.value]
        for ref in refs:
            for target in _callback_targets(ref):
                # Resolve the attr name to any class method in this module.
                for q in fns:
                    if q.split(".")[-1] == target:
                        out.append(q)
    return sorted(set(out))


def _callback_targets(ref: ast.AST) -> List[str]:
    """Method names referenced by a callback expression: `self._x`,
    `lambda ...: self._x(...)`, or a bare function name."""
    if isinstance(ref, ast.Attribute):
        return [ref.attr]
    if isinstance(ref, ast.Name):
        return [ref.id]
    if isinstance(ref, ast.Lambda):
        return [
            call_name(c).split(".")[-1]
            for c in ast.walk(ref.body)
            if isinstance(c, ast.Call)
        ]
    return []


def _module_relpath_index(project: Project) -> Dict[str, str]:
    """Dotted module name -> relpath for every module in the linted tree
    (``ray_tpu/_private/rpc.py`` -> ``ray_tpu._private.rpc``; packages
    map their ``__init__.py`` too)."""
    out: Dict[str, str] = {}
    for mod in project.modules:
        rel = mod.relpath
        if not rel.endswith(".py"):
            continue
        dotted = rel[:-3].replace("/", ".")
        if dotted.endswith(".__init__"):
            dotted = dotted[: -len(".__init__")]
        out[dotted] = rel
    return out


def _import_aliases(
    mod: Module, mod_index: Dict[str, str]
) -> Tuple[Dict[str, str], Dict[str, Tuple[str, str]]]:
    """Alias maps from every import statement in the module (module
    scope AND function-local — this tree imports lazily for cycle
    avoidance, and a lazy import is exactly how helper modules are
    reached from handlers).

    Returns (module_aliases: alias -> relpath,
             symbol_aliases: alias -> (relpath, symbol))."""
    mod_aliases: Dict[str, str] = {}
    sym_aliases: Dict[str, Tuple[str, str]] = {}
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    # `import a.b as x` binds x to module a.b
                    rel = mod_index.get(a.name)
                    if rel:
                        mod_aliases[a.asname] = rel
                else:
                    # `import a.b` binds the name `a` (the TOP package),
                    # not a.b — resolving `a` to a.b would send alias
                    # lookups into the wrong module.
                    top = a.name.split(".")[0]
                    rel = mod_index.get(top)
                    if rel:
                        mod_aliases[top] = rel
        elif isinstance(node, ast.ImportFrom):
            if node.level:
                continue  # relative imports: out of scope
            base = node.module or ""
            for a in node.names:
                full = f"{base}.{a.name}" if base else a.name
                rel = mod_index.get(full)
                if rel:
                    # `from ray_tpu._private import rpc` — a module alias
                    mod_aliases[a.asname or a.name] = rel
                elif base in mod_index:
                    # `from ray_tpu._private.rpc import call_idempotent`
                    sym_aliases[a.asname or a.name] = (mod_index[base], a.name)
    return mod_aliases, sym_aliases


def _callees(
    mod: Module,
    q: str,
    fn: ast.AST,
    fns_by_mod: Dict[str, Dict[str, ast.AST]],
    mod_aliases: Dict[str, str],
    sym_aliases: Dict[str, Tuple[str, str]],
) -> Set[_Node]:
    cls = q.split(".")[0] if "." in q else None
    fns = fns_by_mod[mod.relpath]
    out: Set[_Node] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        if cn.startswith("self.") and cls:
            cand = f"{cls}.{cn[5:]}"
            if cand in fns:
                out.add((mod.relpath, cand))
        elif "." not in cn:
            if cn in fns:
                out.add((mod.relpath, cn))
            elif cn in sym_aliases:
                rel, sym = sym_aliases[cn]
                target_fns = fns_by_mod.get(rel, {})
                if sym in target_fns:
                    out.add((rel, sym))
                elif f"{sym}.__init__" in target_fns:
                    out.add((rel, f"{sym}.__init__"))
        else:
            # alias.func(...) / alias.Class(...) through an imported module
            head, rest = cn.split(".", 1)
            rel = mod_aliases.get(head)
            if rel is None or "." in rest:
                continue  # deeper attribute chains: unresolved by design
            target_fns = fns_by_mod.get(rel, {})
            if rest in target_fns:
                out.add((rel, rest))
            elif f"{rest}.__init__" in target_fns:
                out.add((rel, f"{rest}.__init__"))
    return out


def check_project(project: Project) -> Iterable[Violation]:
    mods_by_rel = {m.relpath: m for m in project.modules}
    fns_by_mod = {m.relpath: _fn_index(m) for m in project.modules}
    mod_index = _module_relpath_index(project)
    alias_cache: Dict[str, Tuple[Dict[str, str], Dict[str, Tuple[str, str]]]] = {}

    def aliases(rel: str):
        got = alias_cache.get(rel)
        if got is None:
            got = alias_cache[rel] = _import_aliases(mods_by_rel[rel], mod_index)
        return got

    # Per-function memo of blocking sites + outgoing edges.
    site_cache: Dict[_Node, List[Tuple[str, int]]] = {}
    edge_cache: Dict[_Node, Set[_Node]] = {}

    def sites(node: _Node) -> List[Tuple[str, int]]:
        got = site_cache.get(node)
        if got is None:
            rel, q = node
            fn = fns_by_mod[rel][q]
            in_async = isinstance(fn, ast.AsyncFunctionDef)
            got = []
            for n in _own_nodes(fn):
                if isinstance(n, ast.Call):
                    kind = _blocking(n, in_async)
                    if kind:
                        got.append((kind, n.lineno))
            site_cache[node] = got
        return got

    def edges(node: _Node) -> Set[_Node]:
        got = edge_cache.get(node)
        if got is None:
            rel, q = node
            mod_aliases, sym_aliases = aliases(rel)
            got = edge_cache[node] = _callees(
                mods_by_rel[rel], q, fns_by_mod[rel][q], fns_by_mod,
                mod_aliases, sym_aliases,
            )
        return got

    out: List[Violation] = []
    reported: Set[Tuple[str, str, int]] = set()
    for mod in project.modules:
        fns = fns_by_mod[mod.relpath]
        if not fns:
            continue
        for entry in _entries(mod, fns):
            root: _Node = (mod.relpath, entry)
            seen = {root}
            frontier: List[Tuple[_Node, Tuple[str, ...]]] = [(root, (entry,))]
            depth = 0
            while frontier and depth < _MAX_DEPTH:
                nxt: List[Tuple[_Node, Tuple[str, ...]]] = []
                for node, trail in frontier:
                    rel, q = node
                    for kind, lineno in sites(node):
                        if (rel, q, lineno) in reported:
                            continue
                        reported.add((rel, q, lineno))
                        via = (
                            "" if len(trail) == 1
                            else " via " + " -> ".join(trail[1:])
                        )
                        origin = (
                            "" if rel == mod.relpath
                            else f" (entry in {mod.relpath})"
                        )
                        out.append(
                            Violation(
                                check=name,
                                path=rel,
                                line=lineno,
                                symbol=q,
                                tag=f"{kind}@{entry}",
                                message=(
                                    f"{kind} reachable from handler/pubsub "
                                    f"entry point {entry}{origin}{via} — this "
                                    "blocks the RPC dispatch loop / reader "
                                    "thread; defer to a worker thread or use "
                                    "asyncio.sleep in async handlers"
                                ),
                            )
                        )
                    for callee in edges(node):
                        if callee not in seen:
                            seen.add(callee)
                            nxt.append((callee, trail + (callee[1],)))
                frontier = nxt
                depth += 1
    return out
