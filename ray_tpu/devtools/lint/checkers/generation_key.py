"""generation-key: rendezvous/checkpoint keys go through canonical helpers.

PR 4's elastic plane hangs correctness off two key formats:

- collective rendezvous KV keys ``<group>/gen<G>/<rank>`` plus the
  ``<group>/gen`` marker — built ONLY by
  ``util/collective/cpu_group.py`` (``_key``/``_gen_key``) and reaped by
  ``util/collective/collective.py``;
- generation-scoped checkpoint dirs ``checkpoint_gGGG_NNNNNN_rankR`` —
  built ONLY by ``train/_internal/session.py`` and parsed by
  ``train/base_trainer.py``.

A hand-rolled key string anywhere else silently bypasses generation
discipline: a stale-format writer can collide with (or regress) a bumped
generation, which is exactly the resume-dir overwrite desync PR 4 fixed.
The checker flags any string literal or f-string fragment outside the
canonical modules that builds either shape (``.../gen<digit|{|<|/|end>``
or ``checkpoint_g...``).  Docstrings are exempt (they may *describe* the
format).
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List

from ray_tpu.devtools.lint.core import Module, Violation, is_docstring

name = "generation-key"

_CANONICAL_FILES = (
    "ray_tpu/util/collective/cpu_group.py",
    "ray_tpu/util/collective/collective.py",
    "ray_tpu/train/_internal/session.py",
    "ray_tpu/train/base_trainer.py",
)

# "/gen" followed by a digit, an interpolation hole, a separator, or
# end-of-string (the marker key) — but not a word like "/general".
_GEN_KEY = re.compile(r"/gen(?=\d|\{|<|/|$)")
_CKPT_KEY = re.compile(r"checkpoint_g(?=\d|\{)")


def _fragments(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, ast.JoinedStr):
        # Render interpolation holes as "{" so the regexes can anchor on
        # them: f"{g}/gen{n}/{r}" -> "{/gen{/{".
        parts = []
        for v in node.values:
            if isinstance(v, ast.Constant) and isinstance(v.value, str):
                parts.append(v.value)
            else:
                parts.append("{")
        return ["".join(parts)]
    return []


def check(mod: Module) -> Iterable[Violation]:
    if mod.relpath in _CANONICAL_FILES:
        return []
    out: List[Violation] = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant):
            if not isinstance(node.value, str) or is_docstring(mod, node):
                continue
            # Skip fragments nested in a JoinedStr (handled there).
            parent = mod.parents.get(node)
            if isinstance(parent, ast.JoinedStr):
                continue
            frags = [node.value]
        elif isinstance(node, ast.JoinedStr):
            frags = _fragments(node)
        else:
            continue
        for frag in frags:
            which = None
            if _GEN_KEY.search(frag):
                which = "rendezvous key"
            elif _CKPT_KEY.search(frag):
                which = "checkpoint dir"
            if which:
                out.append(
                    Violation(
                        check=name,
                        path=mod.relpath,
                        line=node.lineno,
                        symbol=mod.enclosing_qualname(node),
                        tag=f"{which}:{frag[:40]}",
                        message=(
                            f"hand-rolled generation-scoped {which} string "
                            f"{frag[:60]!r} — use the canonical helpers "
                            "(cpu_group._key/_gen_key for rendezvous, "
                            "session checkpoint naming for dirs); a bypassed "
                            "format breaks generation discipline"
                        ),
                    )
                )
                break
    return out
