"""import-cycle: module-level import cycles across the project.

A cycle of **module-scope** imports (``import a`` / ``from a import b``
executed at import time, not inside a function) is a latent crash: it
works only while callers happen to import the participants in one lucky
order, and the first new entry point that starts at the "wrong" module
dies with a partially-initialized module.  The codebase's convention is
to break cycles with function-local imports — this checker enforces
that the convention actually holds by building the module-scope import
graph over every project file and reporting each strongly-connected
component (Tarjan) of size > 1 (or a self-loop).

Imports inside ``if TYPE_CHECKING:`` blocks are ignored (they never run).
One violation is emitted per cycle, anchored at its lexicographically
first module's offending import line, with the full cycle in the
message; the suppression tag is the sorted member list, so a baseline
entry survives line drift and only goes stale when the cycle is
actually broken.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.core import Module, Project, Violation

name = "import-cycle"


def _module_name(relpath: str) -> str:
    """Dotted module name for a project-relative path.
    ``ray_tpu/a/b.py`` -> ``ray_tpu.a.b``; ``__init__.py`` names its
    package."""
    p = relpath[:-3] if relpath.endswith(".py") else relpath
    parts = p.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def _in_type_checking(mod: Module, node: ast.AST) -> bool:
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, ast.If):
            t = cur.test
            if (isinstance(t, ast.Name) and t.id == "TYPE_CHECKING") or (
                isinstance(t, ast.Attribute) and t.attr == "TYPE_CHECKING"
            ):
                return True
        cur = mod.parents.get(cur)
    return False


def _module_scope(mod: Module, node: ast.AST) -> bool:
    """True when the import executes at import time (module scope or a
    module-level ``if``/``try`` — but not inside any function/class-body
    function)."""
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        cur = mod.parents.get(cur)
    return True


def _edges(mod: Module, known: Dict[str, str]) -> Dict[str, int]:
    """Module-scope import targets of ``mod`` that are project modules:
    target module name -> first import line."""
    out: Dict[str, int] = {}
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.Import, ast.ImportFrom)):
            continue
        if not _module_scope(mod, node) or _in_type_checking(mod, node):
            continue
        targets: List[str] = []
        if isinstance(node, ast.Import):
            targets = [a.name for a in node.names]
        else:
            base = node.module or ""
            if node.level:  # relative import: resolve against my package
                pkg_parts = _module_name(mod.relpath).split(".")
                if not mod.relpath.endswith("__init__.py"):
                    pkg_parts = pkg_parts[:-1]
                cut = len(pkg_parts) - (node.level - 1)
                if cut < 0:
                    continue
                base = ".".join(pkg_parts[:cut] + ([base] if base else []))
            # ``from a.b import c``: c may be a submodule or an attribute
            # — prefer the submodule when one exists in the project.
            for a in node.names:
                sub = f"{base}.{a.name}" if base else a.name
                targets.append(sub if sub in known else base)
        for t in targets:
            # Walk up: "import a.b.c" binds a, but EXECUTES a.b.c (and
            # its parents) — the edge goes to the deepest known module.
            while t and t not in known:
                t = t.rsplit(".", 1)[0] if "." in t else ""
            if t and t != _module_name(mod.relpath):
                out.setdefault(t, node.lineno)
    return out


def check_project(project: Project) -> Iterable[Violation]:
    known: Dict[str, str] = {}  # module name -> relpath
    by_rel: Dict[str, Module] = {}
    for mod in project.modules:
        known[_module_name(mod.relpath)] = mod.relpath
        by_rel[mod.relpath] = mod
    graph: Dict[str, Dict[str, int]] = {}
    for mod in project.modules:
        graph[_module_name(mod.relpath)] = _edges(mod, known)

    # Tarjan SCC (iterative).
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    sccs: List[List[str]] = []
    counter = [0]

    def strongconnect(root: str):
        work: List[Tuple[str, Optional[iter]]] = [(root, None)]
        while work:
            node, it = work.pop()
            if it is None:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
                it = iter(graph.get(node, ()))
            recurse = False
            for succ in it:
                if succ not in index:
                    work.append((node, it))
                    work.append((succ, None))
                    recurse = True
                    break
                if succ in on_stack:
                    low[node] = min(low[node], index[succ])
            if recurse:
                continue
            if low[node] == index[node]:
                scc = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    scc.append(w)
                    if w == node:
                        break
                sccs.append(scc)
            if work:
                parent = work[-1][0]
                low[parent] = min(low[parent], low[node])

    for n in graph:
        if n not in index:
            strongconnect(n)

    out: List[Violation] = []
    for scc in sccs:
        members = sorted(scc)
        cyclic = len(members) > 1 or (
            members and members[0] in graph.get(members[0], ())
        )
        if not cyclic:
            continue
        anchor = members[0]
        rel = known[anchor]
        # Line: the anchor's first module-scope import into the cycle.
        line = min(
            (ln for t, ln in graph.get(anchor, {}).items() if t in scc),
            default=1,
        )
        out.append(
            Violation(
                check=name,
                path=rel,
                line=line,
                symbol="<module>",
                tag="cycle:" + ">".join(members),
                message=(
                    "module-level import cycle: "
                    + " -> ".join(members + [members[0]])
                    + " — break it with a function-local import"
                ),
            )
        )
    return out
