"""thread-lifecycle: every spawned thread needs a daemon flag or a join path.

PR 4 shipped the exact bug this guards: survivors' session loop threads
were never retired before the elastic group re-formed, so a stale loop
thread raced the new generation's rendezvous.  The rule: every
``threading.Thread(...)`` spawn site must satisfy one of

- ``daemon=True`` passed to the constructor (fire-and-forget helper that
  must not block interpreter exit), or
- the created thread handle (``self._x = threading.Thread(...)`` or a
  local/module name) has ``.daemon = True`` assigned, or a ``.join(``
  call on the same handle somewhere in the module — i.e. a retire path
  exists.

A thread that is neither daemonized nor joined outlives its owner
silently: it pins interpreter shutdown and keeps mutating state its
owner already tore down.
"""

from __future__ import annotations

import ast
import re
from typing import Iterable, List, Optional

from ray_tpu.devtools.lint.core import Module, Violation, call_name

name = "thread-lifecycle"


def _thread_ctor(node: ast.Call) -> bool:
    return call_name(node) in ("threading.Thread", "Thread")


def _daemon_kwarg_true(node: ast.Call) -> bool:
    for kw in node.keywords:
        if kw.arg == "daemon" and isinstance(kw.value, ast.Constant):
            return kw.value.value is True
    return False


def _assign_target(mod: Module, call: ast.Call) -> Optional[str]:
    """The handle the Thread object lands in: 'self.X' / bare name, or
    None for an anonymous spawn (``threading.Thread(...).start()``)."""
    parent = mod.parents.get(call)
    # threading.Thread(...).start() — anonymous but started immediately;
    # walk up through the Attribute/Call chain.
    if isinstance(parent, ast.Assign) and len(parent.targets) == 1:
        t = parent.targets[0]
        if isinstance(t, ast.Name):
            return t.id
        if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) and \
                t.value.id == "self":
            return f"self.{t.attr}"
    return None


def _module_has_join_or_daemon(mod: Module, handle: str) -> bool:
    """Any `<handle>.join(` call or `<handle>.daemon = True` assignment in
    the module.  Matched on the attribute name for self-handles so the
    join may live in another method (stop/close/retire)."""
    attr = handle.split(".")[-1]
    join_pat = re.compile(
        r"(?:self\.|\b)" + re.escape(attr) + r"\s*\.\s*join\s*\("
    )
    daemon_pat = re.compile(
        r"(?:self\.|\b)" + re.escape(attr) + r"\s*\.\s*daemon\s*=\s*True"
    )
    return bool(join_pat.search(mod.source) or daemon_pat.search(mod.source))


def check(mod: Module) -> Iterable[Violation]:
    out: List[Violation] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _thread_ctor(node):
            continue
        if _daemon_kwarg_true(node):
            continue
        handle = _assign_target(mod, node)
        if handle and _module_has_join_or_daemon(mod, handle):
            continue
        what = f"thread handle {handle!r}" if handle else "anonymous thread"
        out.append(
            Violation(
                check=name,
                path=mod.relpath,
                line=node.lineno,
                symbol=mod.enclosing_qualname(node),
                tag=f"handle={handle or '<anonymous>'}",
                message=(
                    f"threading.Thread spawn with no lifecycle: {what} is "
                    "neither daemon=True nor joined anywhere in this module — "
                    "daemonize it or give it a retire/join path (the PR 4 "
                    "survivor-loop bug class)"
                ),
            )
        )
    return out
