"""trace-orphan: ``record_span`` calls must pin their context explicitly.

``tracing.record_span()`` records at the AMBIENT contextvar when no
``context=`` keyword is given.  Every dataplane consumer (serve replica
dispatch, compiled-DAG executor loops, podracer intake) runs on a
long-lived thread or task whose ambient context is whatever the LAST
inbound frame installed — an implicit-context ``record_span`` there is
a latent orphan: it silently parents one request's span under another
request's (or a stale actor-start) context, and the timeline shows a
broken or cross-wired trace.  That is exactly the resident-executor
re-parenting bug this checker pins: passing ``context=
tracing.current_context()`` is the same single contextvar read, but it
states at the call site that the author CHOSE the ambient context, and
it survives a refactor that moves the call off the frame-scoped path.

Flagged: any call named ``record_span`` (bare or attribute) without an
explicit ``context=`` keyword.  Allowed: ``record_event_span`` (a
deliberate fresh-root event) and ``start_span`` (mints and restores its
own context), plus the tracing module itself (it owns the default).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu.devtools.lint.core import Module, Violation

name = "trace-orphan"

_EXEMPT_FILES = ("ray_tpu/util/tracing/__init__.py",)


def check(mod: Module) -> Iterable[Violation]:
    if mod.relpath in _EXEMPT_FILES:
        return []
    out: List[Violation] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        func = node.func
        if isinstance(func, ast.Name):
            fname = func.id
        elif isinstance(func, ast.Attribute):
            fname = func.attr
        else:
            continue
        if fname != "record_span":
            continue
        if any(kw.arg == "context" for kw in node.keywords):
            continue
        out.append(
            Violation(
                check=name,
                path=mod.relpath,
                line=node.lineno,
                symbol=mod.enclosing_qualname(node),
                tag="record_span",
                message=(
                    "record_span() without an explicit context= falls back "
                    "to the ambient contextvar — on a long-lived executor "
                    "thread that orphans or cross-wires the span under "
                    "whatever frame installed context last; pass context= "
                    "(tracing.current_context() if the ambient context is "
                    "truly what you mean)"
                ),
            )
        )
    return out
