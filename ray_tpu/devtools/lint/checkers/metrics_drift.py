"""metrics-drift: the observability catalog must match the instruments.

``docs/observability.md`` is the operator's map of every built-in metric;
it goes stale the moment someone adds an instrument to
``_private/telemetry.py`` (or anywhere via ``util.metrics``) without a
catalog row — or deletes one and leaves the row behind.  This checker
diffs the two in both directions:

- an instrument created in code (``Counter/Gauge/Histogram("name", ...)``
  with a literal name) but absent from the catalog table -> violation at
  the creation site;
- a catalog row naming an instrument no code creates -> violation at the
  docs line (wildcard rows like ``test_*`` are ignored).

It also flags **unbounded-cardinality label values** at record sites:
passing ``tags={...}`` where a value is an f-string or ``str(<id-like>)``
mints a new time series per distinct value — ids, addresses, and paths
must never become label values (the GCS metrics table and every scrape
grow without bound).
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, Iterable, List, Tuple

from ray_tpu.devtools.lint.core import Module, Project, Violation, call_name

name = "metrics-drift"

DOCS_RELPATH = "docs/observability.md"

_INSTRUMENT_CLASSES = ("Counter", "Gauge", "Histogram")
_META_KWARGS = {"description", "tag_keys", "boundaries"}
_ID_LIKE = re.compile(
    r"(^|_)(id|uuid|addr|address|host|port|path|key|token|trace|span)s?$"
)

_EXEMPT_DIRS = ("ray_tpu/devtools/",)
_EXEMPT_FILES = ("ray_tpu/util/metrics.py",)


def _instrument_calls(mod: Module) -> List[Tuple[str, int]]:
    out: List[Tuple[str, int]] = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node).split(".")[-1]
        if cn not in _INSTRUMENT_CLASSES:
            continue
        if not node.args or not isinstance(node.args[0], ast.Constant) or \
                not isinstance(node.args[0].value, str):
            continue
        # Distinguish a util.metrics instrument from e.g.
        # collections.Counter("x"): require metric-shaped metadata.
        has_meta = any(kw.arg in _META_KWARGS for kw in node.keywords) or (
            len(node.args) >= 2
            and isinstance(node.args[1], ast.Constant)
            and isinstance(node.args[1].value, str)
        )
        if not has_meta:
            continue
        out.append((node.args[0].value, node.lineno))
    return out


def _catalog_names(docs_path: str) -> Tuple[Dict[str, int], List[str]]:
    """Backticked instrument names from the '## Metric catalog' table,
    plus wildcard family rows (``test_*``) as fnmatch patterns — an
    instrument matching a documented family needs no literal row."""
    names: Dict[str, int] = {}
    patterns: List[str] = []
    try:
        with open(docs_path, "r", encoding="utf-8") as fh:
            lines = fh.read().splitlines()
    except OSError:
        return names, patterns
    in_catalog = False
    for i, line in enumerate(lines, start=1):
        if line.startswith("## "):
            in_catalog = line.strip() == "## Metric catalog"
            continue
        if not in_catalog or not line.lstrip().startswith("|"):
            continue
        cells = [c.strip() for c in line.strip().strip("|").split("|")]
        if not cells or cells[0] in ("name", "") or set(cells[0]) <= {"-", " "}:
            continue
        m = re.match(r"`([A-Za-z0-9_*]+)`", cells[0])
        if m:
            if "*" in m.group(1):
                patterns.append(m.group(1))
            else:
                names[m.group(1)] = i
    return names, patterns


def _suspicious_tag_value(v: ast.AST) -> bool:
    if isinstance(v, ast.JoinedStr):
        return any(isinstance(p, ast.FormattedValue) for p in v.values)
    if isinstance(v, ast.Call) and call_name(v) == "str" and v.args:
        inner = v.args[0]
        label = ""
        if isinstance(inner, ast.Name):
            label = inner.id
        elif isinstance(inner, ast.Attribute):
            label = inner.attr
        return bool(_ID_LIKE.search(label))
    return False


def check_project(project: Project) -> Iterable[Violation]:
    out: List[Violation] = []
    created: Dict[str, Tuple[str, int]] = {}
    for mod in project.modules:
        if mod.relpath in _EXEMPT_FILES or any(
            mod.relpath.startswith(d) for d in _EXEMPT_DIRS
        ):
            continue
        for metric_name, line in _instrument_calls(mod):
            created.setdefault(metric_name, (mod.relpath, line))
        # Unbounded-cardinality tag values at record sites.
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            leaf = call_name(node).split(".")[-1]
            if leaf not in ("inc", "observe", "set", "bound"):
                continue
            for kw in node.keywords:
                if kw.arg != "tags" or not isinstance(kw.value, ast.Dict):
                    continue
                for k, v in zip(kw.value.keys, kw.value.values):
                    if v is not None and _suspicious_tag_value(v):
                        key_label = (
                            k.value if isinstance(k, ast.Constant) else "<expr>"
                        )
                        out.append(
                            Violation(
                                check=name,
                                path=mod.relpath,
                                line=node.lineno,
                                symbol=mod.enclosing_qualname(node),
                                tag=f"cardinality:{key_label}",
                                message=(
                                    f"label {key_label!r} gets an interpolated/"
                                    "id-like value — unbounded label "
                                    "cardinality mints a new series per value; "
                                    "use a bounded enum or drop the label"
                                ),
                            )
                        )
    docs_abs = os.path.join(project.root, DOCS_RELPATH)
    catalog, family_patterns = _catalog_names(docs_abs)
    if not catalog and not os.path.exists(docs_abs):
        return out  # fixture trees without docs only get cardinality checks

    from fnmatch import fnmatchcase

    for metric_name, (rel, line) in sorted(created.items()):
        if any(fnmatchcase(metric_name, p) for p in family_patterns):
            continue  # covered by a documented wildcard family row
        if metric_name not in catalog:
            out.append(
                Violation(
                    check=name,
                    path=rel,
                    line=line,
                    symbol=metric_name,
                    tag=f"undocumented:{metric_name}",
                    message=(
                        f"instrument {metric_name!r} is not in the "
                        f"{DOCS_RELPATH} metric catalog — add a row "
                        "(name, type, tags, meaning)"
                    ),
                )
            )
    for metric_name, line in sorted(catalog.items()):
        if metric_name not in created:
            out.append(
                Violation(
                    check=name,
                    path=DOCS_RELPATH,
                    line=line,
                    symbol=metric_name,
                    tag=f"orphaned:{metric_name}",
                    message=(
                        f"catalog row {metric_name!r} names an instrument no "
                        "code creates — delete the row or restore the metric"
                    ),
                )
            )
    return out
