"""lock-order: acquisition-order cycles and locks held across blocking calls.

Builds the lock acquisition graph over every ``threading.Lock``/``RLock``
declaration in the tree:

- **nodes** — declared locks, identified as ``<relpath>::<Class>.<attr>``
  for ``self.x = threading.Lock()`` instance locks (identity is the
  class attribute: all instances share the ordering discipline),
  ``<relpath>::<name>`` for module-level locks, with ``[*]`` marking
  dict-of-locks collections.
- **edges** — ``with A: ... with B:`` static nesting anywhere in the
  tree adds A -> B (nested function bodies do NOT inherit the held set:
  a closure defined under a lock does not run under it).
- **cycles** — any strongly-connected component with two or more locks
  (or a self-edge on a non-RLock) is a potential deadlock: two threads
  taking the locks in opposite orders can each block on the other.

Separately, while at least one lock is statically held, these direct
calls are flagged as *blocking-under-lock*: ``time.sleep(...)`` (non-zero),
RPC ``.call(...)``/``call_idempotent(...)``, and ``<thread>.join(...)``.
A lock held across an RPC couples every thread contending on that lock
to the remote peer's latency (and to its failure/retry budget).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.core import Module, Project, Violation, call_name, dotted

name = "lock-order"

_LOCK_CTORS = {
    "threading.Lock",
    "threading.RLock",
    "Lock",
    "RLock",
}


def _lock_ctor(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Call) and call_name(node) in _LOCK_CTORS:
        return call_name(node)
    return None


def _declared_locks(mod: Module) -> Dict[str, str]:
    """Map local lock handle -> node id.  Handles:
    ``self.attr`` (keyed per enclosing class), module-level names, and
    ``self.attr[...]`` dict-of-locks values."""
    locks: Dict[str, str] = {}
    for node in ast.walk(mod.tree):
        ctor = None
        targets: List[ast.AST] = []
        if isinstance(node, ast.Assign):
            ctor = _lock_ctor(node.value)
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            ctor = _lock_ctor(node.value)
            targets = [node.target]
        if not ctor:
            continue
        rlock = ctor.endswith("RLock")
        for t in targets:
            if isinstance(t, ast.Name):
                scope = mod.enclosing_qualname(node)
                if scope == "<module>":
                    handle = t.id
                else:
                    # class-body lock (shared across instances) or a
                    # function-local lock; key it under the scope.
                    handle = f"{scope}.{t.id}" if "." not in scope else t.id
                locks[handle] = _node_id(mod, handle, rlock)
            elif isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                    and t.value.id == "self":
                cls = mod.enclosing_qualname(node).split(".")[0]
                handle = f"{cls}.self.{t.attr}"
                locks[handle] = _node_id(mod, f"{cls}.{t.attr}", rlock)
            elif isinstance(t, ast.Subscript):
                base = dotted(t.value)
                if base.startswith("self."):
                    cls = mod.enclosing_qualname(node).split(".")[0]
                    handle = f"{cls}.{base}[*]"
                    locks[handle] = _node_id(mod, f"{cls}.{base[5:]}[*]", rlock)
    return locks


def _node_id(mod: Module, label: str, rlock: bool) -> str:
    return f"{mod.relpath}::{label}" + ("#rlock" if rlock else "")


_BLOCKING_SLEEP = ("time.sleep", "_time.sleep")


def _blocking_kind(node: ast.Call) -> Optional[str]:
    cn = call_name(node)
    if cn in _BLOCKING_SLEEP:
        if node.args and isinstance(node.args[0], ast.Constant) and \
                node.args[0].value == 0:
            return None
        return "time.sleep"
    if cn.endswith(".call") or cn.endswith("call_idempotent") or \
            cn.endswith("call_idempotent_async"):
        return "rpc call"
    if cn.endswith(".join"):
        base = cn[: -len(".join")].lower()
        if "thread" in base or "flusher" in base or "worker" in base:
            return "thread join"
    return None


class _Graph:
    def __init__(self):
        self.edges: Dict[str, Set[str]] = {}
        self.sites: Dict[Tuple[str, str], Tuple[str, int, str]] = {}

    def add(self, a: str, b: str, mod: Module, line: int, symbol: str):
        self.edges.setdefault(a, set()).add(b)
        self.edges.setdefault(b, set())
        self.sites.setdefault((a, b), (mod.relpath, line, symbol))


def _sccs(edges: Dict[str, Set[str]]) -> List[List[str]]:
    """Tarjan strongly-connected components (iterative)."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    for root in edges:
        if root in index:
            continue
        work: List[Tuple[str, int]] = [(root, 0)]
        while work:
            node, pi = work.pop()
            if pi == 0:
                index[node] = low[node] = counter[0]
                counter[0] += 1
                stack.append(node)
                on_stack.add(node)
            recursed = False
            succs = sorted(edges.get(node, ()))
            for i in range(pi, len(succs)):
                s = succs[i]
                if s not in index:
                    work.append((node, i + 1))
                    work.append((s, 0))
                    recursed = True
                    break
                if s in on_stack:
                    low[node] = min(low[node], index[s])
            if recursed:
                continue
            for s in succs:
                if s in low and s in on_stack:
                    low[node] = min(low[node], low[s])
            if low[node] == index[node]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == node:
                        break
                out.append(comp)
    return out


def _own_calls(stmt: ast.stmt):
    """Call nodes in this statement's own expressions — pruning nested
    statement bodies (handled by recursion) and nested function bodies
    (they don't run under the lock)."""
    todo = [
        c
        for c in ast.iter_child_nodes(stmt)
        if not isinstance(c, (ast.stmt, ast.ExceptHandler))
    ]
    while todo:
        n = todo.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Call):
            yield n
        todo.extend(
            c for c in ast.iter_child_nodes(n) if not isinstance(c, ast.stmt)
        )


def _walk_withs(
    mod: Module,
    body: List[ast.stmt],
    held: List[str],
    locks: Dict[str, str],
    cls: Optional[str],
    symbol: str,
    graph: _Graph,
    out: List[Violation],
) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue  # closures don't inherit the held set
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            acquired: List[str] = []
            for item in stmt.items:
                node_id = _resolve_lock(item.context_expr, locks, cls)
                if node_id:
                    acquired.append(node_id)
                    for h in held:
                        if h != node_id:
                            graph.add(h, node_id, mod, stmt.lineno, symbol)
            _walk_withs(
                mod, stmt.body, held + acquired, locks, cls, symbol, graph, out
            )
            continue
        if held:
            for sub in _own_calls(stmt):
                kind = _blocking_kind(sub)
                if kind:
                    lock_label = held[-1].split("::", 1)[-1]
                    out.append(
                        Violation(
                            check=name,
                            path=mod.relpath,
                            line=sub.lineno,
                            symbol=symbol,
                            tag=f"blocking:{kind}@{lock_label}",
                            message=(
                                f"{kind} while holding lock "
                                f"{lock_label!r} — every thread contending "
                                "on this lock stalls for the full blocking "
                                "call; move it outside the critical section"
                            ),
                        )
                    )
        # Recurse into compound statements (their With children matter).
        for child_body in _child_bodies(stmt):
            _walk_withs(mod, child_body, held, locks, cls, symbol, graph, out)


def _child_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    out = []
    for field_name in ("body", "orelse", "finalbody"):
        b = getattr(stmt, field_name, None)
        if b:
            out.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        out.append(h.body)
    return out


def _resolve_lock(
    expr: ast.AST, locks: Dict[str, str], cls: Optional[str]
) -> Optional[str]:
    d = dotted(expr)
    if not d:
        return None
    for prefix in ("self.", "cls."):
        if d.startswith(prefix) and cls:
            bare = d[len(prefix):]
            for key in (f"{cls}.{d}", f"{cls}.self.{bare}", f"{cls}.{bare}"):
                hit = locks.get(key)
                if hit:
                    return hit
            return None
    return locks.get(d)


def check_project(project: Project) -> Iterable[Violation]:
    out: List[Violation] = []
    graph = _Graph()
    for mod in project.modules:
        locks = _declared_locks(mod)
        if not locks:
            continue
        for qual, fn in mod.iter_functions():
            # For methods, the first qualname component is the class —
            # it scopes `self.<attr>` lock handles.  For module-level
            # functions it's the function name, which matches no class
            # handle, so `self.` lookups just miss (harmless).
            _walk_withs(mod, fn.body, [], locks, qual.split(".")[0], qual, graph, out)

    for comp in _sccs(graph.edges):
        self_loop = len(comp) == 1 and comp[0] in graph.edges.get(comp[0], ())
        if len(comp) < 2 and not self_loop:
            continue
        if self_loop and comp[0].endswith("#rlock"):
            continue  # re-entrant by construction
        comp_sorted = sorted(comp)
        site = None
        for (a, b), s in sorted(graph.sites.items()):
            if a in comp and b in comp:
                site = s
                break
        path, line, symbol = site if site else (comp_sorted[0].split("::")[0], 1, "<module>")
        pretty = " -> ".join(c.replace("#rlock", "") for c in comp_sorted)
        out.append(
            Violation(
                check=name,
                path=path,
                line=line,
                symbol=symbol,
                tag=f"cycle:{'|'.join(comp_sorted)}",
                message=(
                    f"lock acquisition cycle (potential deadlock): {pretty} — "
                    "threads taking these locks in different orders can "
                    "deadlock; establish one global order or collapse the locks"
                ),
            )
        )
    return out
