"""retry-gate: every retry/poll loop must ride a ``_private/retry.py`` policy.

PR 1 replaced the fixed-interval ``time.sleep`` retry loops scattered
across the core with the unified decorrelated-jitter policies; this
checker keeps new ones from creeping back in.  Two patterns are flagged:

1. ``time.sleep(<non-zero numeric constant>)`` lexically inside a
   ``while``/``for`` loop.  Policy-driven loops sleep a *variable*
   (``bo.next_delay()``), so a constant interval in a loop is either a
   hand-rolled retry/poll loop (fix: ``retry.<POLICY>.start()``) or a
   deliberate fixed-cadence background loop (baseline it with a reason).
   ``time.sleep(0)`` — a bare scheduler yield — is exempt.

2. a ``while`` loop wrapping a ``try``/``except`` whose handler retries
   (``continue``/``pass``-falls-through) around a direct RPC ``.call(``,
   in a function that never consults a ``Backoff`` (``next_delay``) and
   doesn't route through ``call_idempotent``.  That's an unbounded
   hand-rolled RPC retry without jitter or a deadline budget.

``_private/retry.py`` itself is exempt (it is the policy layer and its
docstring shows the canonical loop shape).
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from ray_tpu.devtools.lint.core import Module, Violation, call_name

name = "retry-gate"

_EXEMPT_FILES = ("ray_tpu/_private/retry.py",)


def _sleep_callee(node: ast.Call, mod: Module) -> bool:
    cn = call_name(node)
    if cn in ("time.sleep", "_time.sleep"):
        return True
    # `from time import sleep` style
    return cn == "sleep" and "from time import sleep" in mod.source


def _const_seconds(node: ast.Call):
    if node.args and isinstance(node.args[0], ast.Constant):
        v = node.args[0].value
        if isinstance(v, (int, float)):
            return v
    return None


def _loops_enclosing(mod: Module, node: ast.AST) -> bool:
    """Is ``node`` inside a while/for loop without an intervening
    function boundary?"""
    cur = mod.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.While, ast.For, ast.AsyncFor)):
            return True
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return False
        cur = mod.parents.get(cur)
    return False


def _function_uses_backoff(fn: ast.AST) -> bool:
    for sub in ast.walk(fn):
        if isinstance(sub, ast.Call):
            cn = call_name(sub)
            if cn.endswith(".next_delay") or cn.endswith("call_idempotent") or (
                cn.endswith(".start") and ".".join(cn.split(".")[:-1]).isupper()
            ):
                return True
        if isinstance(sub, ast.Attribute) and sub.attr == "next_delay":
            return True
    return False


def _handler_retries(handler: ast.ExceptHandler) -> bool:
    """except-block that loops again: contains continue, or neither
    raise/return/break (falls through to the next iteration)."""
    terminal = (ast.Raise, ast.Return, ast.Break)
    for sub in ast.walk(handler):
        if isinstance(sub, ast.Continue):
            return True
    return not any(
        isinstance(stmt, terminal) for stmt in ast.walk(handler)
    )


def check(mod: Module) -> Iterable[Violation]:
    if mod.relpath in _EXEMPT_FILES:
        return []
    out: List[Violation] = []

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or not _sleep_callee(node, mod):
            continue
        secs = _const_seconds(node)
        if secs is None or secs == 0:
            continue
        if not _loops_enclosing(mod, node):
            continue
        out.append(
            Violation(
                check=name,
                path=mod.relpath,
                line=node.lineno,
                symbol=mod.enclosing_qualname(node),
                tag=f"sleep={secs}",
                message=(
                    f"fixed-interval time.sleep({secs}) in a loop — route the "
                    "delay through a _private/retry.py policy "
                    "(bo = retry.<POLICY>.start(); time.sleep(bo.next_delay()))"
                ),
            )
        )

    # Pattern 2: while > try/except-retry around a direct rpc .call(...)
    for qual, fn in mod.iter_functions():
        if _function_uses_backoff(fn):
            continue
        for loop in ast.walk(fn):
            if not isinstance(loop, ast.While):
                continue
            for stmt in ast.walk(loop):
                if not isinstance(stmt, ast.Try):
                    continue
                has_rpc_call = any(
                    isinstance(c, ast.Call) and call_name(c).endswith(".call")
                    for body_stmt in stmt.body
                    for c in ast.walk(body_stmt)
                )
                if not has_rpc_call:
                    continue
                if any(_handler_retries(h) for h in stmt.handlers):
                    out.append(
                        Violation(
                            check=name,
                            path=mod.relpath,
                            line=stmt.lineno,
                            symbol=qual,
                            tag="handrolled-rpc-retry",
                            message=(
                                "hand-rolled retry loop around an RPC .call() "
                                "without a retry.py policy — use "
                                "retry.<POLICY>.start() for jitter + deadline "
                                "budget, or rpc.call_idempotent for reads"
                            ),
                        )
                    )
                    break  # one report per loop
    return out
