"""Checker registry.  A checker is a module exposing ``name`` and either
``check(module)`` (per-file) or ``check_project(project)`` (whole-tree).
Add new checkers here and in docs/static_analysis.md."""

from ray_tpu.devtools.lint.checkers import (
    blocking_handler,
    generation_key,
    import_cycle,
    lock_order,
    metrics_drift,
    retry_gate,
    rpc_contract,
    shared_state_race,
    thread_lifecycle,
    trace_orphan,
)

ALL_CHECKERS = [
    retry_gate,
    lock_order,
    thread_lifecycle,
    blocking_handler,
    metrics_drift,
    generation_key,
    import_cycle,
    trace_orphan,
    rpc_contract,
    shared_state_race,
]

CHECK_NAMES = [c.name for c in ALL_CHECKERS]
