"""rpc-contract: cross-process RPC surface conformance.

The control plane is stringly-typed RPC (``_private/rpc.py``): a client
``.call("method", payload)`` reaches ``async def rpc_method`` on whatever
handler object the server was built with, ``.push("method", payload)``
reaches ``push_method`` (server side) or an ``on_push=`` dispatcher
comparing the method name against literals (client side).  Nothing ties
the two ends together at import time, so the contract only breaks at
runtime — on the failure path, usually.  This checker rebuilds the whole
surface statically and enforces five invariants:

- **no-handler** — a literal ``.call("x")`` / ``.push("x")`` /
  ``call_idempotent(_, "x")`` site whose method has no ``rpc_x`` /
  ``push_x`` handler and (for pushes) no dispatcher literal anywhere in
  the linted tree: a typo'd endpoint that raises ``method not found`` at
  runtime.
- **dead-endpoint** — an ``rpc_x``/``push_x`` handler no call site,
  string literal, or direct attribute reference anywhere targets: dead
  code on a live dispatch surface (or the call side was deleted and the
  contract silently halved).
- **payload-drift** — a call site passing a dict *literal* payload that
  is missing a key the handler subscripts without a ``.get`` default or
  ``"k" in payload`` guard: a guaranteed ``KeyError`` inside the handler.
- **retry-unsafe** — a ``call_idempotent``/``call_idempotent_async``
  site targeting a handler that neither consumes an idempotency
  ``token`` payload key nor declares itself read-only (docstring or
  comment marker ``rpc-contract: read-only``): the PR 1 double-execute
  class — retries of a non-idempotent write execute it twice.
- **fence-missing** — in a class that defines ``_check_fence``, a
  handler that reads ``node_id`` from its payload and writes ``self``
  state without consulting the fence first: the PR 19
  zombie-resurrection class — a stale incarnation's write lands on
  liveness-adjacent state.

Identity: ``symbol`` is the call-site/handler qualname, ``tag`` is
``method=<name>`` (payload-drift adds ``:missing=<keys>``), so baselines
survive line drift.  Declare a genuinely read-only endpoint by putting
``rpc-contract: read-only`` in the handler's docstring (or a comment on
the ``def`` line); see docs/static_analysis.md.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.core import Module, Project, Violation, call_name

name = "rpc-contract"

_READONLY_MARKER = "rpc-contract: read-only"
_CALLBACK_KWARGS = ("on_push", "on_close", "on_reconnect", "on_disconnect")
_MUTATORS = {
    "append", "add", "update", "pop", "setdefault", "clear", "remove",
    "discard", "extend", "appendleft", "popleft", "insert", "put",
}


@dataclass
class _Handler:
    mod: Module
    qualname: str
    fn: ast.AST  # FunctionDef / AsyncFunctionDef
    kind: str  # "rpc" | "push"
    method: str


@dataclass
class _CallSite:
    mod: Module
    qualname: str
    node: ast.Call
    kind: str  # "call" | "push" | "idempotent"
    method: str
    payload: Optional[ast.AST]


@dataclass
class _Surface:
    rpc: Dict[str, List[_Handler]] = field(default_factory=dict)
    push: Dict[str, List[_Handler]] = field(default_factory=dict)
    # method names a client-side on_push dispatcher compares against
    dispatch_literals: Set[str] = field(default_factory=set)
    sites: List[_CallSite] = field(default_factory=list)
    # weak liveness evidence: every string literal / attribute name in
    # the tree (wrapper helpers pass method names as strings; tests and
    # delegating handlers reference `rpc_x` as an attribute)
    strings: Set[str] = field(default_factory=set)
    attr_refs: Set[str] = field(default_factory=set)


def _first_param(fn: ast.AST) -> Optional[str]:
    args = [a.arg for a in fn.args.args if a.arg not in ("self", "cls")]
    return args[0] if args else None


def _collect_handlers(mod: Module, surface: _Surface) -> None:
    for q, fn in mod.iter_functions():
        base = q.split(".")[-1]
        if "." not in q:
            continue  # handlers are methods on a server class
        for prefix, kind, table in (
            ("rpc_", "rpc", surface.rpc),
            ("push_", "push", surface.push),
        ):
            if base.startswith(prefix) and len(base) > len(prefix):
                method = base[len(prefix):]
                table.setdefault(method, []).append(
                    _Handler(mod, q, fn, kind, method)
                )


def _dispatcher_literals(mod: Module, fns: Dict[str, ast.AST]) -> Set[str]:
    """Method-name literals an ``on_push=`` dispatcher compares its
    method parameter against (``if method == "preempt_job": ...`` /
    ``elif m in ("a", "b")``)."""
    targets: Set[str] = set()
    for node in ast.walk(mod.tree):
        refs: List[ast.AST] = []
        if isinstance(node, ast.Call):
            refs = [kw.value for kw in node.keywords if kw.arg in _CALLBACK_KWARGS]
        elif isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Attribute) and t.attr in _CALLBACK_KWARGS:
                refs = [node.value]
        for ref in refs:
            if isinstance(ref, ast.Attribute):
                targets.add(ref.attr)
            elif isinstance(ref, ast.Name):
                targets.add(ref.id)
            elif isinstance(ref, ast.Lambda):
                for c in ast.walk(ref.body):
                    if isinstance(c, ast.Call):
                        targets.add(call_name(c).split(".")[-1])
    out: Set[str] = set()
    for q, fn in fns.items():
        if q.split(".")[-1] not in targets:
            continue
        params = {a.arg for a in fn.args.args} - {"self", "cls"}
        if not params:
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Compare) or len(node.comparators) != 1:
                continue
            if not (isinstance(node.left, ast.Name) and node.left.id in params):
                continue
            comp = node.comparators[0]
            if isinstance(comp, ast.Constant) and isinstance(comp.value, str):
                out.add(comp.value)
            elif isinstance(comp, (ast.Tuple, ast.List, ast.Set)):
                for el in comp.elts:
                    if isinstance(el, ast.Constant) and isinstance(el.value, str):
                        out.add(el.value)
    return out


def _collect_sites(mod: Module, surface: _Surface) -> None:
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            surface.strings.add(node.value)
        elif isinstance(node, ast.Attribute):
            surface.attr_refs.add(node.attr)
        if not isinstance(node, ast.Call):
            continue
        cn = call_name(node)
        leaf = cn.split(".")[-1]
        kind = None
        method_arg = payload_arg = None
        if leaf in ("call", "push") and "." in cn:
            kind = "call" if leaf == "call" else "push"
            if node.args:
                method_arg = node.args[0]
                payload_arg = node.args[1] if len(node.args) > 1 else None
        elif leaf in ("call_idempotent", "call_idempotent_async"):
            kind = "idempotent"
            if len(node.args) > 1:
                method_arg = node.args[1]
                payload_arg = node.args[2] if len(node.args) > 2 else None
        if kind is None:
            continue
        if not (isinstance(method_arg, ast.Constant)
                and isinstance(method_arg.value, str)):
            continue  # dynamic method name: out of scope
        for kw in node.keywords:
            if kw.arg == "payload":
                payload_arg = kw.value
        surface.sites.append(
            _CallSite(
                mod,
                mod.enclosing_qualname(node),
                node,
                kind,
                method_arg.value,
                payload_arg,
            )
        )


def _required_keys(fn: ast.AST, param: str) -> Set[str]:
    """Keys the handler subscripts off its payload param without a
    guard.  A key is *guarded* (not required from every call site) when
    the handler also reads it via ``param.get("k")`` anywhere (the
    ``if payload.get("k"): ... payload["k"]`` idiom) or tests
    ``"k" in param``."""
    subscripted: Set[str] = set()
    guarded: Set[str] = set()
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name) \
                and node.value.id == param \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str) \
                and isinstance(node.ctx, ast.Load):
            subscripted.add(node.slice.value)
        elif isinstance(node, ast.Compare) and len(node.ops) == 1 \
                and isinstance(node.ops[0], (ast.In, ast.NotIn)) \
                and isinstance(node.comparators[0], ast.Name) \
                and node.comparators[0].id == param \
                and isinstance(node.left, ast.Constant) \
                and isinstance(node.left.value, str):
            guarded.add(node.left.value)
        elif isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == param \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and isinstance(node.args[0].value, str):
            guarded.add(node.args[0].value)
    return subscripted - guarded


def _literal_payload_keys(payload: Optional[ast.AST]) -> Optional[Set[str]]:
    """Keys of a pure dict-literal payload; None when the payload is
    dynamic (a variable, ``**`` expansion, or computed keys) — those
    sites cannot be checked for drift."""
    if not isinstance(payload, ast.Dict):
        return None
    keys: Set[str] = set()
    for k in payload.keys:
        if k is None:  # ** expansion
            return None
        if not (isinstance(k, ast.Constant) and isinstance(k.value, str)):
            return None
        keys.add(k.value)
    return keys


def _reads_payload_key(fn: ast.AST, param: str, key: str) -> bool:
    """Does the handler read ``param[key]`` / ``param.get(key)``?"""
    for node in ast.walk(fn):
        if isinstance(node, ast.Subscript) and isinstance(node.value, ast.Name) \
                and node.value.id == param \
                and isinstance(node.slice, ast.Constant) \
                and node.slice.value == key:
            return True
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "get" \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == param \
                and node.args \
                and isinstance(node.args[0], ast.Constant) \
                and node.args[0].value == key:
            return True
    return False


def _is_read_only(h: _Handler) -> bool:
    doc = ast.get_docstring(h.fn, clean=False) or ""
    if _READONLY_MARKER in doc:
        return True
    # comment marker on the def line or the line above it
    for lineno in (h.fn.lineno - 1, h.fn.lineno - 2):
        if 0 <= lineno < len(h.mod.lines) \
                and _READONLY_MARKER in h.mod.lines[lineno]:
            return True
    return False


def _self_state_writes(fn: ast.AST, mod: Module) -> List[int]:
    """Line numbers where the handler mutates ``self`` state: attribute
    stores, subscript stores on a self attribute, or mutator method
    calls on a self attribute.  Nested function bodies are pruned (they
    run elsewhere)."""
    out: List[int] = []
    todo = list(ast.iter_child_nodes(fn))
    while todo:
        n = todo.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        if isinstance(n, ast.Attribute) and isinstance(n.value, ast.Name) \
                and n.value.id == "self" \
                and isinstance(n.ctx, (ast.Store, ast.Del)):
            out.append(n.lineno)
        elif isinstance(n, ast.Subscript) and isinstance(n.ctx, (ast.Store, ast.Del)):
            v = n.value
            if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                out.append(n.lineno)
        elif isinstance(n, ast.Call) and isinstance(n.func, ast.Attribute) \
                and n.func.attr in _MUTATORS:
            v = n.func.value
            if isinstance(v, ast.Attribute) and isinstance(v.value, ast.Name) \
                    and v.value.id == "self":
                out.append(n.lineno)
        todo.extend(ast.iter_child_nodes(n))
    return sorted(out)


def _fence_call_line(fn: ast.AST) -> Optional[int]:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                call_name(node) in ("self._check_fence", "cls._check_fence"):
            return node.lineno
    return None


def check_project(project: Project) -> Iterable[Violation]:
    surface = _Surface()
    fence_classes: Dict[Tuple[str, str], bool] = {}  # (relpath, class) -> True
    fns_by_mod: Dict[str, Dict[str, ast.AST]] = {}
    for mod in project.modules:
        fns = {q: fn for q, fn in mod.iter_functions()}
        fns_by_mod[mod.relpath] = fns
        _collect_handlers(mod, surface)
        _collect_sites(mod, surface)
        surface.dispatch_literals |= _dispatcher_literals(mod, fns)
        for q in fns:
            if q.endswith("._check_fence") and q.count(".") == 1:
                fence_classes[(mod.relpath, q.split(".")[0])] = True

    out: List[Violation] = []

    # -- no-handler: literal call sites with no handler anywhere --------
    for site in surface.sites:
        m = site.method
        if site.kind in ("call", "idempotent"):
            ok = m in surface.rpc
        else:  # push: server-side push_ handler OR client-side dispatcher
            ok = m in surface.push or m in surface.dispatch_literals
        if not ok:
            want = "rpc_" + m if site.kind != "push" else "push_" + m
            out.append(
                Violation(
                    check=name,
                    path=site.mod.relpath,
                    line=site.node.lineno,
                    symbol=site.qualname,
                    tag=f"no-handler:method={m}",
                    message=(
                        f"RPC {site.kind} targets method {m!r} but no "
                        f"{want} handler (or push dispatcher literal) exists "
                        "anywhere in the linted tree — typo'd or deleted "
                        "endpoint; this fails at runtime with 'method not "
                        "found'"
                    ),
                )
            )

    # -- dead-endpoint: handlers nothing references ---------------------
    called: Dict[str, Set[str]] = {"rpc": set(), "push": set()}
    for site in surface.sites:
        if site.kind in ("call", "idempotent"):
            called["rpc"].add(site.method)
        else:
            called["push"].add(site.method)
    for kind, table in (("rpc", surface.rpc), ("push", surface.push)):
        for method, handlers in table.items():
            if method in called[kind]:
                continue
            if method in surface.strings:
                continue  # wrapper helpers pass method names as strings
            if f"{kind}_{method}" in surface.attr_refs:
                continue  # direct delegation / tests call the method
            for h in handlers:
                out.append(
                    Violation(
                        check=name,
                        path=h.mod.relpath,
                        line=h.fn.lineno,
                        symbol=h.qualname,
                        tag=f"dead-endpoint:method={method}",
                        message=(
                            f"handler {h.qualname} serves method {method!r} "
                            "but no call site, push, string reference, or "
                            "direct attribute reference targets it anywhere "
                            "in the linted tree — dead endpoint; delete it "
                            "or wire the client side"
                        ),
                    )
                )

    # -- payload-drift: dict-literal sites missing required keys --------
    for site in surface.sites:
        table = surface.rpc if site.kind in ("call", "idempotent") else surface.push
        handlers = table.get(site.method)
        if not handlers:
            continue  # no-handler already fired
        provided = _literal_payload_keys(site.payload)
        if provided is None:
            continue
        # every handler for the method must be satisfiable from this site
        for h in handlers:
            param = _first_param(h.fn)
            if param is None:
                continue
            missing = sorted(_required_keys(h.fn, param) - provided)
            if missing:
                out.append(
                    Violation(
                        check=name,
                        path=site.mod.relpath,
                        line=site.node.lineno,
                        symbol=site.qualname,
                        tag=(
                            f"payload-drift:method={site.method}"
                            f":missing={'+'.join(missing)}"
                        ),
                        message=(
                            f"payload for {site.method!r} is missing "
                            f"key(s) {', '.join(repr(k) for k in missing)} "
                            f"that handler {h.qualname} subscripts without "
                            "a .get default — guaranteed KeyError on the "
                            "serving side"
                        ),
                    )
                )

    # -- retry-unsafe: idempotent calls into non-idempotent handlers ----
    for site in surface.sites:
        if site.kind != "idempotent":
            continue
        for h in surface.rpc.get(site.method, ()):
            param = _first_param(h.fn)
            consumes_token = bool(
                param and _reads_payload_key(h.fn, param, "token")
            )
            if consumes_token or _is_read_only(h):
                continue
            out.append(
                Violation(
                    check=name,
                    path=site.mod.relpath,
                    line=site.node.lineno,
                    symbol=site.qualname,
                    tag=f"retry-unsafe:method={site.method}",
                    message=(
                        f"call_idempotent targets {site.method!r} but handler "
                        f"{h.qualname} neither consumes an idempotency "
                        "'token' payload key nor declares itself read-only "
                        f"({_READONLY_MARKER!r} in its docstring) — a retried "
                        "delivery executes the write twice (the PR 1 "
                        "double-execute class)"
                    ),
                )
            )

    # -- fence-missing: unfenced node_id-bearing write handlers ---------
    for kind, table in (("rpc", surface.rpc), ("push", surface.push)):
        for method, handlers in table.items():
            for h in handlers:
                cls = h.qualname.split(".")[0]
                if not fence_classes.get((h.mod.relpath, cls)):
                    continue
                param = _first_param(h.fn)
                if not param or not _reads_payload_key(h.fn, param, "node_id"):
                    continue
                writes = _self_state_writes(h.fn, h.mod)
                if not writes:
                    continue
                fence_at = _fence_call_line(h.fn)
                if fence_at is not None and fence_at <= writes[0]:
                    continue
                out.append(
                    Violation(
                        check=name,
                        path=h.mod.relpath,
                        line=h.fn.lineno,
                        symbol=h.qualname,
                        tag=f"fence-missing:method={method}",
                        message=(
                            f"handler {h.qualname} reads 'node_id' from its "
                            "payload and writes self state "
                            + (
                                f"(first write line {writes[0]}, fence "
                                f"consulted only at line {fence_at}) "
                                if fence_at is not None
                                else f"(first write line {writes[0]}) "
                            )
                            + "without consulting self._check_fence first — "
                            "a zombie incarnation's write lands on "
                            "liveness-adjacent state (the PR 19 class)"
                        ),
                    )
                )

    return out
