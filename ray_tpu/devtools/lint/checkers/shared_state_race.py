"""shared-state-race: cross-thread attribute access with no shared lock.

Every robustness arc added daemon threads to control-plane classes
(rx/intake loops, flushers, sweeper ticks), and every one of those
threads shares ``self`` with the main thread.  This checker runs a
static lockset analysis over each class that spawns threads:

- **contexts** — one per ``threading.Thread(target=...)`` entry point
  (a ``self.method``, a nested closure, or a lambda calling one), plus
  a single ``main`` context covering every other method.  ``__init__``
  is excluded: construction happens-before ``Thread.start``.
- **sites** — every ``self.attr`` read/write reachable from a context's
  entry point through intra-class ``self.method()`` calls, with the
  statically-held lock set carried through the call graph (a method
  called under ``with self._lock:`` inherits the lock).  Writes are
  attribute stores, subscript stores, ``del``, augmented assigns, and
  mutator calls (``.append``/``.pop``/``.update``/...).
- **violation** — an attribute written in one context and read/written
  in another where the two sites' held-lock sets do not intersect.

Idiom allowlist (these patterns are deliberately lock-free here and in
CPython practice):

- *single-writer flag* — every non-``__init__`` write assigns a
  constant (``self._stop = True``): torn reads are impossible, staleness
  is the accepted semantics.
- *append-only counter* — every write is an augmented assign
  (``self.n += 1``): monotonic stats counters.
- *synchronization primitives* — attributes holding ``Event`` /
  ``Condition`` / ``Semaphore`` / ``Barrier`` / ``queue.*`` / ``deque``
  / ``Thread`` objects are themselves thread-safe hand-off points.

``tests/`` modules are skipped: test helpers spawn throwaway threads
whose lifetimes are controlled by the test body, not a lock discipline.
Identity: ``symbol`` is the class qualname, ``tag`` is
``attr=<Class>.<attr>`` — suppressions go in ``.graftlint.toml`` with a
written justification (see docs/static_analysis.md).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, FrozenSet, Iterable, List, Optional, Set, Tuple

from ray_tpu.devtools.lint.core import Module, Project, Violation, call_name, dotted

name = "shared-state-race"

_THREAD_CTORS = {"threading.Thread", "Thread"}
_LOCK_CTORS = {"threading.Lock", "Lock", "threading.RLock", "RLock",
               "threading.Condition", "Condition"}
# Attributes holding these are synchronization/hand-off objects — their
# own methods are thread-safe, so accesses to the attribute are not
# shared-state races.
_SAFE_CTORS = _LOCK_CTORS | {
    "threading.Event", "Event",
    "threading.Semaphore", "Semaphore",
    "threading.BoundedSemaphore", "BoundedSemaphore",
    "threading.Barrier", "Barrier",
    "threading.local",
    "queue.Queue", "Queue",
    "queue.SimpleQueue", "SimpleQueue",
    "queue.LifoQueue", "LifoQueue",
    "queue.PriorityQueue", "PriorityQueue",
    "collections.deque", "deque",
    "threading.Thread", "Thread",
}
_MUTATORS = {
    "append", "add", "update", "pop", "setdefault", "clear", "remove",
    "discard", "extend", "appendleft", "popleft", "insert", "put",
    "popitem",
}


@dataclass(frozen=True)
class _Site:
    ctx: str        # context name ("main" or the thread target's name)
    attr: str
    write: bool
    write_kind: str  # "const" | "aug" | "other" | "" (reads)
    locks: FrozenSet[str]
    line: int
    path: str


class _ClassInfo:
    def __init__(self, mod: Module, qualname: str, node: ast.ClassDef):
        self.mod = mod
        self.qualname = qualname
        self.node = node
        self.methods: Dict[str, ast.AST] = {}
        self.lock_attrs: Set[str] = set()
        self.safe_attrs: Set[str] = set()
        self.thread_targets: List[Tuple[str, ast.AST]] = []  # (ctx name, fn)


def _classes(mod: Module) -> List[_ClassInfo]:
    out: List[_ClassInfo] = []
    for node, q in mod.qualnames.items():
        if isinstance(node, ast.ClassDef):
            out.append(_ClassInfo(mod, q, node))
    for ci in out:
        for n, q in mod.qualnames.items():
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and q.startswith(ci.qualname + ".") \
                    and "." not in q[len(ci.qualname) + 1:]:
                ci.methods[q[len(ci.qualname) + 1:]] = n
    return out


def _scan_attr_types(ci: _ClassInfo) -> None:
    """Find lock/safe attributes from ``self.x = <ctor>()`` assignments
    anywhere in the class body."""
    for fn in ci.methods.values():
        for node in ast.walk(fn):
            targets: List[ast.AST] = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
            if not isinstance(value, ast.Call):
                continue
            ctor = call_name(value)
            for t in targets:
                if isinstance(t, ast.Attribute) and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    if ctor in _LOCK_CTORS:
                        ci.lock_attrs.add(t.attr)
                    if ctor in _SAFE_CTORS:
                        ci.safe_attrs.add(t.attr)


def _thread_target_names(call: ast.Call) -> List[ast.AST]:
    for kw in call.keywords:
        if kw.arg == "target":
            return [kw.value]
    return []


def _scan_thread_targets(ci: _ClassInfo) -> None:
    """Thread entry points spawned by this class: ``target=self.m``,
    ``target=<nested def>``, ``target=lambda: self.m()``."""
    for mname, fn in ci.methods.items():
        nested = {
            n.name: n
            for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call) \
                    or call_name(node) not in _THREAD_CTORS:
                continue
            for ref in _thread_target_names(node):
                if isinstance(ref, ast.Attribute) \
                        and isinstance(ref.value, ast.Name) \
                        and ref.value.id == "self" \
                        and ref.attr in ci.methods:
                    ci.thread_targets.append((ref.attr, ci.methods[ref.attr]))
                elif isinstance(ref, ast.Name) and ref.id in nested:
                    ci.thread_targets.append(
                        (f"{mname}.{ref.id}", nested[ref.id])
                    )
                elif isinstance(ref, ast.Lambda):
                    for c in ast.walk(ref.body):
                        if isinstance(c, ast.Call):
                            leaf = call_name(c)
                            if leaf.startswith("self.") \
                                    and leaf[5:] in ci.methods:
                                ci.thread_targets.append(
                                    (leaf[5:], ci.methods[leaf[5:]])
                                )


def _own_exprs(stmt: ast.stmt):
    """Non-statement nodes in this statement's own expressions, pruning
    nested statements (visited by the body recursion with the right held
    set) and nested function/lambda bodies (they run elsewhere)."""
    todo = [
        c
        for c in ast.iter_child_nodes(stmt)
        if not isinstance(c, (ast.stmt, ast.ExceptHandler))
    ]
    while todo:
        n = todo.pop()
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            continue
        yield n
        todo.extend(
            c for c in ast.iter_child_nodes(n) if not isinstance(c, ast.stmt)
        )


def _child_bodies(stmt: ast.stmt) -> List[List[ast.stmt]]:
    out = []
    for field_name in ("body", "orelse", "finalbody"):
        b = getattr(stmt, field_name, None)
        if b:
            out.append(b)
    for h in getattr(stmt, "handlers", []) or []:
        out.append(h.body)
    return out


class _Collector:
    def __init__(self, ci: _ClassInfo):
        self.ci = ci
        self.sites: List[_Site] = []
        # (method name, inherited locks) -> visited, to bound recursion
        self._memo: Set[Tuple[str, FrozenSet[str]]] = set()

    def run(self, ctx: str, fn: ast.AST, held: FrozenSet[str]) -> None:
        self._ctx = ctx
        self._walk_fn(fn, held)

    def _walk_fn(self, fn: ast.AST, held: FrozenSet[str]) -> None:
        self._walk_body(fn.body, held)

    def _resolve_lock(self, expr: ast.AST) -> Optional[str]:
        d = dotted(expr)
        if d.startswith("self.") and d[5:] in self.ci.lock_attrs:
            return d[5:]
        return None

    def _walk_body(self, body: List[ast.stmt], held: FrozenSet[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue  # closures don't inherit the held set
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                acquired = set()
                for item in stmt.items:
                    lock = self._resolve_lock(item.context_expr)
                    if lock:
                        acquired.add(lock)
                self._scan_exprs(stmt, held)
                self._walk_body(stmt.body, held | frozenset(acquired))
                continue
            if isinstance(stmt, ast.Try):
                # manual-acquire idiom: `lock.acquire(); try: ... finally:
                # lock.release()` — the try body runs under the lock
                released = set()
                for fin in stmt.finalbody:
                    for n in ast.walk(fin):
                        if isinstance(n, ast.Call) \
                                and isinstance(n.func, ast.Attribute) \
                                and n.func.attr == "release":
                            lock = self._resolve_lock(n.func.value)
                            if lock:
                                released.add(lock)
                if released:
                    self._scan_exprs(stmt, held)
                    self._walk_body(stmt.body, held | frozenset(released))
                    for h in stmt.handlers:
                        self._walk_body(h.body, held | frozenset(released))
                    self._walk_body(stmt.orelse, held | frozenset(released))
                    self._walk_body(stmt.finalbody, held)
                    continue
            self._scan_exprs(stmt, held)
            for child in _child_bodies(stmt):
                self._walk_body(child, held)

    def _scan_exprs(self, stmt: ast.stmt, held: FrozenSet[str]) -> None:
        ci = self.ci
        parents = ci.mod.parents
        for n in _own_exprs(stmt):
            # intra-class call: propagate the held set into the callee
            if isinstance(n, ast.Call):
                cn = call_name(n)
                if cn.startswith("self.") and cn[5:] in ci.methods:
                    callee = cn[5:]
                    key = (self._ctx, callee, held)
                    if key not in self._memo:
                        self._memo.add(key)
                        self._walk_fn(ci.methods[callee], held)
            if not (isinstance(n, ast.Attribute)
                    and isinstance(n.value, ast.Name)
                    and n.value.id == "self"):
                continue
            attr = n.attr
            if attr in ci.safe_attrs or attr in ci.methods:
                continue
            write = False
            write_kind = ""
            if isinstance(n.ctx, (ast.Store, ast.Del)):
                write = True
                parent = parents.get(n)
                if isinstance(parent, ast.AugAssign) and parent.target is n:
                    write_kind = "aug"
                elif isinstance(parent, ast.Assign) \
                        and isinstance(parent.value, ast.Constant):
                    write_kind = "const"
                else:
                    write_kind = "other"
            else:
                parent = parents.get(n)
                if isinstance(parent, ast.Subscript) \
                        and parent.value is n \
                        and isinstance(parent.ctx, (ast.Store, ast.Del)):
                    write, write_kind = True, "other"
                elif isinstance(parent, ast.Attribute) \
                        and parent.value is n \
                        and parent.attr in _MUTATORS:
                    gp = parents.get(parent)
                    if isinstance(gp, ast.Call) and gp.func is parent:
                        write, write_kind = True, "other"
            self.sites.append(
                _Site(
                    ctx=self._ctx,
                    attr=attr,
                    write=write,
                    write_kind=write_kind,
                    locks=held,
                    line=n.lineno,
                    path=ci.mod.relpath,
                )
            )


def _check_class(ci: _ClassInfo) -> Iterable[Violation]:
    _scan_attr_types(ci)
    _scan_thread_targets(ci)
    if not ci.thread_targets:
        return []

    collector = _Collector(ci)
    target_names = {t for t, _ in ci.thread_targets}
    # Private methods invoked from inside the class are helpers, not
    # entry points — they run in whatever context (and under whatever
    # locks) their callers hold, so walking them as independent "main"
    # roots would fabricate unlocked access paths.
    internally_called: Set[str] = set()
    for fn in ci.methods.values():
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                cn = call_name(node)
                if cn.startswith("self.") and cn[5:] in ci.methods:
                    internally_called.add(cn[5:])
    seen_targets = set()
    for tname, fn in ci.thread_targets:
        if tname in seen_targets:
            continue
        seen_targets.add(tname)
        collector.run(tname, fn, frozenset())
    for mname, fn in ci.methods.items():
        if mname == "__init__" or mname in target_names:
            continue
        if mname.endswith("_locked"):
            # convention: *_locked helpers require the caller to hold the
            # class lock — they are analyzed through their callers (where
            # an unlocked call path still surfaces), not as entry points
            continue
        if mname.startswith("_") and mname in internally_called:
            continue
        collector.run("main", fn, frozenset())

    by_attr: Dict[str, List[_Site]] = {}
    for s in collector.sites:
        by_attr.setdefault(s.attr, []).append(s)

    out: List[Violation] = []
    for attr, sites in sorted(by_attr.items()):
        ctxs = {s.ctx for s in sites}
        if len(ctxs) < 2:
            continue
        writes = [s for s in sites if s.write]
        if not writes:
            continue  # set in __init__, read everywhere: immutable config
        if all(w.write_kind == "const" for w in writes):
            continue  # single-writer flag idiom
        if all(w.write_kind == "aug" for w in writes):
            continue  # append-only counter idiom
        offending: Optional[Tuple[_Site, _Site]] = None
        for w in writes:
            for s in sites:
                if s.ctx != w.ctx and not (w.locks & s.locks):
                    offending = (w, s)
                    break
            if offending:
                break
        if not offending:
            continue
        w, s = offending
        other = "written" if s.write else "read"
        out.append(
            Violation(
                check=name,
                path=ci.mod.relpath,
                line=w.line,
                symbol=ci.qualname,
                tag=f"attr={ci.qualname}.{attr}",
                message=(
                    f"self.{attr} is written in thread context "
                    f"{w.ctx!r} (line {w.line}) and {other} in context "
                    f"{s.ctx!r} (line {s.line}) with no common lock held "
                    "at both sites — potential data race; guard both "
                    "sides with one lock, hand off through a queue, or "
                    "baseline with a written justification"
                ),
            )
        )
    return out


def check_project(project: Project) -> Iterable[Violation]:
    out: List[Violation] = []
    for mod in project.modules:
        if mod.relpath.startswith("tests/"):
            continue  # test helpers: thread lifetimes are test-controlled
        for ci in _classes(mod):
            out.extend(_check_class(ci))
    return out
