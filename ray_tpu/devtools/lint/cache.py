"""Content-addressed AST cache for the lint gate.

Parsing ~270 files costs more wall time than any single checker, and the
tree for a given file is a pure function of its bytes — so the gate
memoizes ``ast.parse`` keyed on the sha256 of the source.  One pickle
file per source file, named by content hash, under
``<root>/.graftlint_cache/`` (gitignored): an edit changes the hash and
simply misses, so there is no invalidation protocol, and stale entries
from old revisions are pruned opportunistically once the directory
outgrows the tree being linted.

The cache is best-effort everywhere: any OSError / corrupt pickle falls
back to a fresh parse.  Entries are versioned by the running
interpreter's (major, minor) because pickled AST nodes do not travel
across Python versions.  Disable with GRAFTLINT_NO_CACHE=1 (or the CLI's
``--no-cache``).
"""

from __future__ import annotations

import ast
import hashlib
import os
import pickle
import sys
from typing import Optional

__all__ = ["AstCache"]

_VERSION = f"py{sys.version_info[0]}{sys.version_info[1]}v1"


class AstCache:
    def __init__(self, root: str, enabled: bool = True):
        self.dir = os.path.join(root, ".graftlint_cache")
        self.enabled = enabled and os.environ.get("GRAFTLINT_NO_CACHE") != "1"
        self.hits = 0
        self.misses = 0
        self._ready = False

    def _ensure_dir(self) -> bool:
        if not self._ready:
            try:
                os.makedirs(self.dir, exist_ok=True)
            except OSError:
                self.enabled = False
                return False
            self._ready = True
        return True

    @staticmethod
    def _key(src: str) -> str:
        return hashlib.sha256(src.encode("utf-8", "surrogatepass")).hexdigest()

    def _path(self, key: str) -> str:
        return os.path.join(self.dir, f"{key}.{_VERSION}.astpkl")

    def parse(self, src: str, filename: str) -> ast.AST:
        """``ast.parse`` with cache; SyntaxError propagates (and is never
        cached — a bad file re-parses each run, which is both rare and
        the signal the gate must re-surface)."""
        if not self.enabled:
            return ast.parse(src, filename=filename)
        key = self._key(src)
        path = self._path(key)
        try:
            with open(path, "rb") as fh:
                tree = pickle.load(fh)
            self.hits += 1
            return tree
        except (OSError, pickle.UnpicklingError, EOFError, AttributeError, ValueError):
            pass
        tree = ast.parse(src, filename=filename)
        self.misses += 1
        self._store(path, key, tree)
        return tree

    def _store(self, path: str, key: str, tree: ast.AST) -> None:
        if not self._ensure_dir():
            return
        tmp = f"{path}.tmp.{os.getpid()}"
        try:
            with open(tmp, "wb") as fh:
                pickle.dump(tree, fh, protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, path)  # atomic: concurrent gates never read torn pickles
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass

    def prune(self, keep_under: int = 2048) -> None:
        """Drop oldest entries once the dir holds more than ``keep_under``
        files (several tree-revisions of slack before any eviction)."""
        if not self.enabled or not self._ready:
            return
        try:
            names = os.listdir(self.dir)
            if len(names) <= keep_under:
                return
            paths = [os.path.join(self.dir, n) for n in names]
            paths.sort(key=lambda p: os.path.getmtime(p))
            for p in paths[: len(paths) - keep_under]:
                try:
                    os.unlink(p)
                except OSError:
                    pass
        except OSError:
            pass
