"""graftlint — AST-based concurrency & invariant analyzer for ray_tpu.

Four PRs of robustness work accumulated distributed-systems invariants
that only lived in reviewers' heads: every retry loop must ride a
``_private/retry.py`` policy, no blocking sleeps on RPC dispatch or
pubsub threads, spawned threads need a daemon flag or a join path, lock
acquisition order must stay acyclic, the metrics catalog must match the
instruments that actually exist, and rendezvous/checkpoint keys must go
through the canonical generation-scoped helpers.  graftlint walks the
whole ``ray_tpu/`` tree (stdlib ``ast`` only, no third-party deps) and
enforces them on every PR, with a checked-in suppression baseline
(``.graftlint.toml``) so accepted exceptions are explicit and diffable.

Run it::

    python -m ray_tpu.devtools.lint [paths ...]

Checker catalog and suppression format: docs/static_analysis.md.
"""

from ray_tpu.devtools.lint.core import LintResult, Violation, run_lint

__all__ = ["LintResult", "Violation", "run_lint"]
