"""graftlint command line: ``python -m ray_tpu.devtools.lint`` / ``graftlint``.

Exit codes: 0 = clean (all violations suppressed or none), 1 = unsuppressed
violations (or parse errors / stale baseline entries under --strict),
2 = bad invocation.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from ray_tpu.devtools.lint import baseline as baseline_mod
from ray_tpu.devtools.lint import core


def _default_paths(root: str) -> List[str]:
    return [os.path.join(root, "ray_tpu")]


def main(argv: Optional[List[str]] = None) -> int:
    from ray_tpu.devtools.lint.checkers import CHECK_NAMES

    ap = argparse.ArgumentParser(
        prog="graftlint",
        description=(
            "AST-based concurrency & invariant analyzer for the ray_tpu "
            "distributed core (see docs/static_analysis.md)"
        ),
    )
    ap.add_argument("paths", nargs="*", help="files/dirs to lint (default: ray_tpu/)")
    ap.add_argument(
        "--root",
        default=None,
        help="repo root (default: nearest dir with pyproject.toml)",
    )
    ap.add_argument(
        "--baseline",
        default=None,
        help=f"suppression baseline (default: <root>/{baseline_mod.DEFAULT_NAME})",
    )
    ap.add_argument(
        "--no-baseline", action="store_true", help="ignore the baseline entirely"
    )
    ap.add_argument(
        "--select",
        default=None,
        help="comma-separated checks to run (default: all)",
    )
    ap.add_argument(
        "--format", choices=("text", "json"), default="text", dest="fmt"
    )
    ap.add_argument(
        "--json",
        action="store_const",
        const="json",
        dest="fmt",
        help="shorthand for --format json (machine-readable report)",
    )
    ap.add_argument(
        "--no-cache",
        action="store_true",
        help="parse fresh instead of using the content-hash AST cache",
    )
    ap.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed violations and their reasons",
    )
    ap.add_argument(
        "--strict",
        action="store_true",
        help="stale (unmatched) baseline entries and parse errors fail the run",
    )
    ap.add_argument(
        "--write-baseline",
        metavar="PATH",
        default=None,
        help=(
            "write a bootstrap baseline covering today's unsuppressed "
            "violations (reasons are TODO placeholders: fill them in or the "
            "baseline will not load)"
        ),
    )
    ap.add_argument("--list-checks", action="store_true")
    args = ap.parse_args(argv)

    if args.list_checks:
        for n in CHECK_NAMES:
            print(n)
        return 0

    select = None
    if args.select:
        select = [s.strip() for s in args.select.split(",") if s.strip()]
        unknown = set(select) - set(CHECK_NAMES) - {"bad-suppression"}
        if unknown:
            print(f"graftlint: unknown checks: {', '.join(sorted(unknown))}",
                  file=sys.stderr)
            return 2

    if args.paths:
        paths = args.paths
        root = args.root or core.repo_root_for(paths[0])
    else:
        root = args.root or core.repo_root_for(os.getcwd())
        paths = _default_paths(root)
        if not os.path.isdir(paths[0]):
            print(f"graftlint: no ray_tpu/ under {root}; pass paths explicitly",
                  file=sys.stderr)
            return 2

    bl = None
    if not args.no_baseline:
        try:
            if args.baseline:
                bl = baseline_mod.load(args.baseline)
            else:
                bl = baseline_mod.load_default(root)
        except (baseline_mod.BaselineError, OSError) as e:
            print(f"graftlint: bad baseline: {e}", file=sys.stderr)
            return 2

    result = core.run_lint(
        paths, root=root, baseline=bl, select=select,
        use_cache=not args.no_cache,
    )

    if args.write_baseline:
        n = baseline_mod.write(args.write_baseline, result.unsuppressed)
        print(f"graftlint: wrote {n} entries to {args.write_baseline} "
              "(fill in the TODO reasons before checking it in)")
        return 0

    if args.fmt == "json":
        run_checks = select if select is not None else CHECK_NAMES
        by_check = {c: 0 for c in run_checks}
        for v in result.unsuppressed:
            by_check[v.check] = by_check.get(v.check, 0) + 1
        print(json.dumps(
            {
                "files_checked": result.files_checked,
                "elapsed_s": round(result.elapsed_s, 3),
                "checks_run": list(run_checks),
                "unsuppressed": len(result.unsuppressed),
                "suppressed": len(result.suppressed),
                "by_check": by_check,
                "cache": {"hits": result.cache_hits, "misses": result.cache_misses},
                "violations": [v.__dict__ for v in result.violations],
                "parse_errors": [v.__dict__ for v in result.parse_errors],
                "unused_baseline": result.unused_baseline,
            },
            indent=2,
        ))
    else:
        for v in result.unsuppressed:
            print(v.format())
        if args.show_suppressed:
            for v in result.suppressed:
                print(f"[suppressed:{v.suppressed_by}] {v.format()}")
        for v in result.parse_errors:
            print(v.format(), file=sys.stderr)
        for e in result.unused_baseline:
            print(
                "graftlint: stale baseline entry (matches nothing): "
                f"{e['check']} @ {e['path']}"
                + (f" [{e.get('symbol')}]" if e.get("symbol") else ""),
                file=sys.stderr,
            )
        n_bad = len(result.unsuppressed)
        summary = (
            f"graftlint: {result.files_checked} files, "
            f"{n_bad} unsuppressed violation(s), "
            f"{len(result.suppressed)} suppressed, "
            f"{result.elapsed_s:.2f}s"
        )
        print(summary, file=sys.stderr if n_bad else sys.stdout)

    failed = bool(result.unsuppressed)
    if args.strict and (result.parse_errors or result.unused_baseline):
        failed = True
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
