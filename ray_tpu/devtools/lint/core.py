"""graftlint engine: file walking, AST modules, suppressions, reporting.

Two checker shapes plug in (see ``checkers/__init__.py``):

- **module checkers** — ``check(module) -> Iterable[Violation]``; run once
  per parsed file.  Purely local reasoning (retry loops, thread spawns,
  generation keys, handler reachability within a module).
- **project checkers** — ``check_project(project) -> Iterable[Violation]``;
  run once with every parsed module in hand.  Cross-module reasoning
  (the lock acquisition graph, the metrics catalog diff).

Violations are identified for suppression purposes by
``(check, path, symbol, tag)`` — the *symbol* is the enclosing function/
class qualname and the *tag* a checker-chosen stable discriminator — so
baselines survive unrelated line drift.  Two suppression channels:

- inline: ``# graftlint: disable=<check>[,<check>] -- <reason>`` on the
  flagged line, or standing alone on the line above.  A disable comment
  without a reason is itself a violation (``bad-suppression``).
- baseline: ``[[suppress]]`` entries in ``.graftlint.toml`` at the repo
  root (see baseline.py); every entry must carry a reason.
"""

from __future__ import annotations

import ast
import os
import re
import time
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

__all__ = [
    "Violation",
    "Module",
    "Project",
    "LintResult",
    "run_lint",
    "repo_root_for",
]

_DISABLE_RE = re.compile(
    r"#\s*graftlint:\s*disable=([A-Za-z0-9_,\-* ]+?)\s*(?:--\s*(.*\S))?\s*$"
)


@dataclass
class Violation:
    """One finding.  ``path`` is repo-root-relative with posix separators."""

    check: str
    path: str
    line: int
    message: str
    symbol: str = "<module>"
    tag: str = ""
    # Filled in by the engine: how this violation was suppressed (if it was).
    suppressed_by: Optional[str] = None  # "inline" | "baseline" | None

    def key(self) -> Tuple[str, str, str, str]:
        return (self.check, self.path, self.symbol, self.tag)

    def format(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol != "<module>" else ""
        return f"{self.path}:{self.line}: {self.check}:{sym} {self.message}"


class Module:
    """One parsed source file plus the derived maps checkers need."""

    def __init__(self, abspath: str, relpath: str, source: str, tree: ast.AST):
        self.abspath = abspath
        self.relpath = relpath  # posix, relative to the repo root
        self.source = source
        self.lines = source.splitlines()
        self.tree = tree
        self._parents: Optional[Dict[ast.AST, ast.AST]] = None
        self._qualnames: Optional[Dict[ast.AST, str]] = None
        # line -> (set of check names or {"*"}, reason or None)
        self.inline_disables: Dict[int, Tuple[set, Optional[str]]] = {}
        self.bad_suppressions: List[Violation] = []
        self._scan_inline_suppressions()

    # -- inline suppressions ------------------------------------------------
    def _scan_inline_suppressions(self) -> None:
        for i, line in enumerate(self.lines, start=1):
            m = _DISABLE_RE.search(line)
            if not m:
                continue
            checks = {c.strip() for c in m.group(1).split(",") if c.strip()}
            reason = m.group(2)
            # A comment-only line suppresses the next line; a trailing
            # comment suppresses its own line.
            target = i + 1 if line.lstrip().startswith("#") else i
            if not reason:
                self.bad_suppressions.append(
                    Violation(
                        check="bad-suppression",
                        path=self.relpath,
                        line=i,
                        message=(
                            "inline graftlint disable without a reason — use "
                            "'# graftlint: disable=<check> -- <why this is ok>'"
                        ),
                        symbol=self.qualname_at_line(i),
                        tag=",".join(sorted(checks)),
                    )
                )
                continue
            existing = self.inline_disables.get(target)
            if existing:
                existing[0].update(checks)
            else:
                self.inline_disables[target] = (set(checks), reason)

    def is_disabled(self, check: str, line: int) -> bool:
        ent = self.inline_disables.get(line)
        return bool(ent and (check in ent[0] or "*" in ent[0]))

    # -- structural maps ----------------------------------------------------
    @property
    def parents(self) -> Dict[ast.AST, ast.AST]:
        if self._parents is None:
            self._parents = {}
            for node in ast.walk(self.tree):
                for child in ast.iter_child_nodes(node):
                    self._parents[child] = node
        return self._parents

    @property
    def qualnames(self) -> Dict[ast.AST, str]:
        """FunctionDef/AsyncFunctionDef/ClassDef node -> dotted qualname."""
        if self._qualnames is None:
            self._qualnames = {}

            def visit(node: ast.AST, prefix: str) -> None:
                for child in ast.iter_child_nodes(node):
                    if isinstance(
                        child,
                        (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
                    ):
                        q = f"{prefix}.{child.name}" if prefix else child.name
                        self._qualnames[child] = q
                        visit(child, q)
                    else:
                        visit(child, prefix)

            visit(self.tree, "")
        return self._qualnames

    def enclosing_qualname(self, node: ast.AST) -> str:
        """Qualname of the innermost function/class containing ``node``."""
        cur = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                return self.qualnames.get(cur, cur.name)
            cur = self.parents.get(cur)
        return "<module>"

    def qualname_at_line(self, line: int) -> str:
        """Best-effort qualname for a line (used for suppression records)."""
        best = "<module>"
        best_span = None
        for node, q in self.qualnames.items():
            end = getattr(node, "end_lineno", node.lineno)
            if node.lineno <= line <= end:
                span = end - node.lineno
                if best_span is None or span < best_span:
                    best, best_span = q, span
        return best

    def iter_functions(self):
        """Yield (qualname, node) for every function/method, outermost first."""
        for node, q in self.qualnames.items():
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield q, node


@dataclass
class Project:
    root: str
    modules: List[Module] = field(default_factory=list)

    def module(self, relpath: str) -> Optional[Module]:
        for m in self.modules:
            if m.relpath == relpath:
                return m
        return None


@dataclass
class LintResult:
    violations: List[Violation]
    parse_errors: List[Violation]
    unused_baseline: List[dict]
    files_checked: int
    elapsed_s: float
    cache_hits: int = 0
    cache_misses: int = 0

    @property
    def unsuppressed(self) -> List[Violation]:
        return [v for v in self.violations if v.suppressed_by is None]

    @property
    def suppressed(self) -> List[Violation]:
        return [v for v in self.violations if v.suppressed_by is not None]


def repo_root_for(path: str) -> str:
    """Walk up from ``path`` to the directory holding ``pyproject.toml``
    (or ``.graftlint.toml``); fall back to the path itself."""
    start = os.path.abspath(path if os.path.isdir(path) else os.path.dirname(path))
    cur = start
    while True:
        if any(
            os.path.exists(os.path.join(cur, marker))
            for marker in ("pyproject.toml", ".graftlint.toml", ".git")
        ):
            return cur
        parent = os.path.dirname(cur)
        if parent == cur:
            # No marker anywhere above: the starting DIRECTORY is the
            # root (never the file itself — relpaths must stay filenames
            # so inline/baseline suppression matching keeps working).
            return start
        cur = parent


def _discover(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p):
            if p.endswith(".py"):
                out.append(os.path.abspath(p))
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        out.append(os.path.abspath(os.path.join(dirpath, fn)))
    return out


def run_lint(
    paths: Sequence[str],
    root: Optional[str] = None,
    baseline: Optional[object] = None,
    select: Optional[Sequence[str]] = None,
    use_cache: bool = True,
) -> LintResult:
    """Parse every file under ``paths`` and run the checkers.

    ``baseline`` is a ``baseline.Baseline`` (or None to skip baseline
    matching); ``select`` limits to the named checks; ``use_cache``
    memoizes ``ast.parse`` on source content hash (see cache.py).
    """
    from ray_tpu.devtools.lint import checkers as _checkers
    from ray_tpu.devtools.lint.cache import AstCache

    t0 = time.perf_counter()
    root = os.path.abspath(root or repo_root_for(paths[0] if paths else "."))
    files = _discover(paths)
    ast_cache = AstCache(root, enabled=use_cache)
    modules: List[Module] = []
    parse_errors: List[Violation] = []
    for f in files:
        rel = os.path.relpath(f, root).replace(os.sep, "/")
        try:
            with open(f, "r", encoding="utf-8") as fh:
                src = fh.read()
            tree = ast_cache.parse(src, filename=f)
        except (SyntaxError, UnicodeDecodeError, OSError) as e:
            parse_errors.append(
                Violation(
                    check="parse-error",
                    path=rel,
                    line=getattr(e, "lineno", 0) or 0,
                    message=f"could not parse: {e}",
                )
            )
            continue
        modules.append(Module(f, rel, src, tree))
    ast_cache.prune()

    project = Project(root=root, modules=modules)
    selected = set(select) if select else None

    violations: List[Violation] = []
    for mod in modules:
        if selected is None or "bad-suppression" in selected:
            violations.extend(mod.bad_suppressions)
    for checker in _checkers.ALL_CHECKERS:
        if selected is not None and checker.name not in selected:
            continue
        if hasattr(checker, "check_project"):
            violations.extend(checker.check_project(project))
        else:
            for mod in modules:
                violations.extend(checker.check(mod))

    # Apply inline suppressions (bad-suppression itself can't be silenced).
    by_path = {m.relpath: m for m in modules}
    for v in violations:
        if v.check == "bad-suppression":
            continue
        mod = by_path.get(v.path)
        if mod is not None and mod.is_disabled(v.check, v.line):
            v.suppressed_by = "inline"

    # Apply the baseline.
    unused: List[dict] = []
    if baseline is not None:
        unused = baseline.apply(violations)

    violations.sort(key=lambda v: (v.path, v.line, v.check))
    return LintResult(
        violations=violations,
        parse_errors=parse_errors,
        unused_baseline=unused,
        files_checked=len(modules),
        elapsed_s=time.perf_counter() - t0,
        cache_hits=ast_cache.hits,
        cache_misses=ast_cache.misses,
    )


# -- small shared AST helpers (imported by checkers) ------------------------

def call_name(node: ast.Call) -> str:
    """Dotted name of a call's callee: ``time.sleep`` -> "time.sleep",
    ``self._kv(...)`` -> "self._kv", bare ``sleep(...)`` -> "sleep"."""
    return dotted(node.func)


def dotted(node: ast.AST) -> str:
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    elif isinstance(cur, ast.Subscript):
        inner = dotted(cur.value)
        parts.append(f"{inner}[*]" if inner else "[*]")
    elif parts:
        parts.append("?")
    return ".".join(reversed(parts))


def is_docstring(mod: Module, node: ast.Constant) -> bool:
    """True when ``node`` is the docstring expression of its scope."""
    parent = mod.parents.get(node)
    if not isinstance(parent, ast.Expr):
        return False
    scope = mod.parents.get(parent)
    body = getattr(scope, "body", None)
    return bool(body) and body[0] is parent
