"""The ``.graftlint.toml`` suppression baseline.

Accepted violations live in one checked-in file at the repo root so every
exception to an invariant is explicit, reviewed, and diffable::

    version = 1

    [[suppress]]
    check = "retry-gate"
    path = "ray_tpu/_private/worker.py"
    symbol = "ReferenceTracker._ensure_flusher_locked"
    reason = "fixed-cadence background flusher, not a retry loop"

Matching is by ``(check, path)`` plus, when present, ``symbol`` and
``tag`` — line numbers are deliberately NOT part of identity so baselines
survive unrelated edits.  ``reason`` is mandatory: a reasonless entry
fails the load.  Entries that match nothing are reported so the baseline
can only shrink as fixes land.

Python 3.10 has no ``tomllib``; since we also must not add third-party
deps, ``_parse_toml`` implements the small TOML subset the baseline
uses (top-level scalars + ``[[suppress]]`` array-of-tables with string/
int/bool values).  When ``tomllib`` exists it is preferred.
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ray_tpu.devtools.lint.core import Violation

__all__ = ["Baseline", "BaselineError", "load", "write"]

DEFAULT_NAME = ".graftlint.toml"


class BaselineError(ValueError):
    pass


_KV_RE = re.compile(r"^([A-Za-z0-9_\-]+)\s*=\s*(.+)$")


def _parse_value(raw: str, lineno: int):
    raw = raw.strip()
    if raw.startswith('"') and raw.endswith('"') and len(raw) >= 2:
        body = raw[1:-1]
        return re.sub(
            r"\\(.)",
            lambda m: {"n": "\n", "t": "\t", '"': '"', "\\": "\\"}.get(
                m.group(1), m.group(1)
            ),
            body,
        )
    if raw in ("true", "false"):
        return raw == "true"
    try:
        return int(raw)
    except ValueError:
        raise BaselineError(f"line {lineno}: unsupported TOML value: {raw!r}")


def _parse_toml(text: str) -> dict:
    try:
        import tomllib  # Python >= 3.11
    except ModuleNotFoundError:
        tomllib = None
    if tomllib is not None:
        try:
            return tomllib.loads(text)
        except tomllib.TOMLDecodeError as e:
            # Same friendly "bad baseline" path on every Python version.
            raise BaselineError(str(e))
    doc: dict = {}
    current: dict = doc
    for i, line in enumerate(text.splitlines(), start=1):
        stripped = line.strip()
        if not stripped or stripped.startswith("#"):
            continue
        if stripped.startswith("[[") and stripped.endswith("]]"):
            name = stripped[2:-2].strip()
            current = {}
            doc.setdefault(name, []).append(current)
            continue
        if stripped.startswith("[") and stripped.endswith("]"):
            name = stripped[1:-1].strip()
            current = doc.setdefault(name, {})
            continue
        m = _KV_RE.match(stripped)
        if not m:
            raise BaselineError(f"line {i}: cannot parse: {stripped!r}")
        # Strip a trailing comment from unquoted values.
        val = m.group(2)
        if not val.lstrip().startswith('"') and "#" in val:
            val = val.split("#", 1)[0]
        current[m.group(1)] = _parse_value(val, i)
    return doc


@dataclass
class Entry:
    check: str
    path: str
    reason: str
    symbol: Optional[str] = None
    tag: Optional[str] = None
    used: bool = field(default=False, compare=False)

    def matches(self, v: Violation) -> bool:
        if self.check != v.check or self.path != v.path:
            return False
        if self.symbol is not None and self.symbol != v.symbol:
            return False
        if self.tag is not None and self.tag != v.tag:
            return False
        return True

    def as_dict(self) -> dict:
        d = {"check": self.check, "path": self.path}
        if self.symbol is not None:
            d["symbol"] = self.symbol
        if self.tag is not None:
            d["tag"] = self.tag
        d["reason"] = self.reason
        return d


@dataclass
class Baseline:
    path: Optional[str]
    entries: List[Entry] = field(default_factory=list)

    def apply(self, violations: List[Violation]) -> List[dict]:
        """Mark matching violations suppressed; return the entries that
        matched nothing (as dicts, for the 'stale baseline' report)."""
        for v in violations:
            if v.check == "bad-suppression":
                continue
            for e in self.entries:
                if e.matches(v):
                    v.suppressed_by = "baseline"
                    e.used = True
                    break
        return [e.as_dict() for e in self.entries if not e.used]


def load(path: str) -> Baseline:
    with open(path, "r", encoding="utf-8") as fh:
        doc = _parse_toml(fh.read())
    entries: List[Entry] = []
    for i, raw in enumerate(doc.get("suppress", [])):
        if not isinstance(raw, dict):
            raise BaselineError(f"suppress[{i}]: expected a table")
        check = raw.get("check")
        rel = raw.get("path")
        reason = raw.get("reason")
        if not check or not rel:
            raise BaselineError(f"suppress[{i}]: 'check' and 'path' are required")
        if not isinstance(reason, str) or not reason.strip():
            raise BaselineError(
                f"suppress[{i}] ({check} @ {rel}): every baseline entry must "
                "carry a human-readable 'reason'"
            )
        entries.append(
            Entry(
                check=str(check),
                path=str(rel),
                reason=reason.strip(),
                symbol=raw.get("symbol"),
                tag=raw.get("tag"),
            )
        )
    return Baseline(path=path, entries=entries)


def load_default(root: str) -> Optional[Baseline]:
    p = os.path.join(root, DEFAULT_NAME)
    return load(p) if os.path.exists(p) else None


def _quote(s: str) -> str:
    return '"' + s.replace("\\", "\\\\").replace('"', '\\"') + '"'


def write(path: str, violations: List[Violation], reason: str = "TODO: justify") -> int:
    """Write a baseline covering ``violations`` (bootstrap helper for
    ``--write-baseline``).  Collapses duplicates by suppression key."""
    seen = set()
    lines = [
        "# graftlint suppression baseline — every entry needs a reason.",
        "# Format: docs/static_analysis.md",
        "version = 1",
    ]
    n = 0
    for v in sorted(violations, key=lambda v: (v.path, v.check, v.symbol, v.tag)):
        key = v.key()
        if key in seen:
            continue
        seen.add(key)
        lines += [
            "",
            "[[suppress]]",
            f"check = {_quote(v.check)}",
            f"path = {_quote(v.path)}",
        ]
        if v.symbol != "<module>":
            lines.append(f"symbol = {_quote(v.symbol)}")
        if v.tag:
            lines.append(f"tag = {_quote(v.tag)}")
        lines.append(f"reason = {_quote(reason)}")
        n += 1
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    return n
