import sys

from ray_tpu.devtools.lint.cli import main

sys.exit(main())
