"""Developer tooling shipped with the repo (not part of the runtime API)."""
